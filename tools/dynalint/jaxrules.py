"""The JAX hot-path analysis layer (DL010–DL015).

Built on the jit registry in core.ProjectIndex: every ``jax.jit``/``pjit``
wrapped callable with its ``donate_argnums``/``static_argnums``/
``static_argnames``, every ``shard_map`` site with its declared specs, the
step-thread hot closure (``threading.Thread`` targets plus
catalog.HOT_PATH_ROOTS), and the device-returning closure (functions whose
return value transitively comes from a jit call).

The bug classes these encode are the ones that silently eat serving
efficiency without failing a single test on CPU:

  * DL010 — a host↔device sync on the step thread serializes the device
    pipeline (the BENCH_r05 dispatch-overhead gap);
  * DL011 — a retrace per request turns microseconds into seconds;
  * DL012 — reading a donated buffer is undefined behavior; NOT donating a
    pool doubles its HBM footprint per step;
  * DL013 — a pytree leaf without a PartitionSpec (the QuantPool scale
    leaves) forces whole code paths off the fused kernels;
  * DL014 — a capability gate that downgrades fused→XLA or quantized→bf16
    without accounting for itself is invisible until a benchmark regresses
    (ROADMAP #7's "fp8 + tp>1 silently takes the XLA path");
  * DL015 — a threading.Lock held across ``await``, or two locks taken in
    opposite orders on the step-thread/asyncio boundary, is a deadlock
    waiting for kill-9 churn.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.dynalint.core import (
    Finding,
    FunctionInfo,
    JitInfo,
    ProjectIndex,
    ScanContext,
    ShardMapSite,
    dotted,
    enclosing_function,
    parents,
    qualname,
)

_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}


def _last(name: str | None) -> str:
    return (name or "").rsplit(".", 1)[-1]


def _loaded_names(node: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _own_call(info: FunctionInfo, node: ast.Call) -> bool:
    """Is this call made DIRECTLY by ``info`` (not by a nested def, whose
    body has its own FunctionInfo and gets checked on its own)?"""
    fn = enclosing_function(node)
    while isinstance(fn, ast.Lambda):
        fn = enclosing_function(fn)
    return fn is info.node


# --------------------------------------------------------------------------
# DL010 host-sync-in-hot-path
# --------------------------------------------------------------------------

# calls that force the host to wait for the device regardless of operand
_ALWAYS_SYNC = frozenset({"device_get", "block_until_ready"})
# conversions that force a sync only when fed a device value
_TAINT_SYNC_METHODS = frozenset({"item", "tolist"})
_TAINT_SYNC_NAMES = frozenset({"float", "int", "bool"})
_TAINT_SYNC_NP = frozenset({"asarray", "array"})


class HostSyncInHotPath:
    """DL010: host↔device sync reachable from the engine step loop.

    The step thread owns the device: every ``jax.device_get``/
    ``block_until_ready``/``.item()``/``float(...)``/``np.asarray(...)``
    on a device value it executes is serial time added to EVERY decode
    step — the device sits idle behind the host for the full transfer.
    Deliberate, *accounted* syncs are the discipline this repo already
    has: wrap them in ``with self._phase("...d2h...")`` so the profiler
    attributes the wait (dispatch.d2h_wait / readmit.d2h_wait /
    process.d2h_sync), and DL010 treats the block as exempt. Anything
    else is either hoisted off the step thread or suppressed with the
    reason it must block.

    Hot functions = the transitive closure from ``threading.Thread``
    targets and catalog.HOT_PATH_ROOTS; device values = results of
    jit-registry callables (and of functions that transitively return
    one, e.g. the model-family adapters), tracked through assignments.
    """

    id = "DL010"
    name = "host-sync-in-hot-path"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        project = ctx.project
        if project is None or not project.hot:
            return
        for (path, _qual), info in project.functions.items():
            if path != ctx.path or not project.is_hot(info):
                continue
            yield from self._check_fn(ctx, project, info)

    def _check_fn(self, ctx, project, info) -> Iterable[Finding]:
        tainted = self._device_tainted(project, info)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call) or not _own_call(info, node):
                continue
            name = dotted(node.func) or ""
            last = _last(name)
            hit: str | None = None
            if last in _ALWAYS_SYNC:
                hit = last
            elif last in _TAINT_SYNC_METHODS and isinstance(
                node.func, ast.Attribute
            ):
                if _loaded_names(node.func.value) & tainted:
                    hit = f".{last}()"
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in _TAINT_SYNC_NAMES
                and node.args
                and _loaded_names(node.args[0]) & tainted
            ):
                hit = f"{node.func.id}()"
            elif (
                last in _TAINT_SYNC_NP
                and name.split(".", 1)[0] in ("np", "numpy")
                and node.args
                and _loaded_names(node.args[0]) & tainted
            ):
                hit = f"{name}()"
            if hit is None or self._accounted(node):
                continue
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"{hit} on the step-thread hot path "
                        f"({info.qualname}) blocks the device pipeline "
                        "for the full device->host transfer",
                hint="hoist the sync off the step thread, or account for "
                     "it: wrap in `with self._phase(\"...d2h...\")` so "
                     "the dispatch-overhead profile attributes the wait",
                context=info.qualname,
                detail=f"sync:{info.qualname}:{hit}",
            )

    @staticmethod
    def _device_tainted(project, info) -> set[str]:
        """Local names bound (incl. tuple-unpack) from device-returning
        calls inside this function."""
        tainted: set[str] = set()
        for node in ast.walk(info.node):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            name = dotted(node.value.func)
            if not name or not project.is_device_call(info, name):
                continue
            for t in node.targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for el in elts:
                    if isinstance(el, ast.Name):
                        tainted.add(el.id)
        return tainted

    @staticmethod
    def _accounted(node: ast.AST) -> bool:
        """Inside a ``with self._phase("...d2h...")`` block: the sync is
        deliberate and profiler-attributed — the repo's accounted-sync
        discipline (dispatch.d2h_wait / readmit.d2h_wait /
        process.d2h_sync)."""
        for p in parents(node):
            if not isinstance(p, ast.With):
                continue
            for item in p.items:
                ce = item.context_expr
                if not (
                    isinstance(ce, ast.Call)
                    and _last(dotted(ce.func)) == "_phase"
                    and ce.args
                ):
                    continue
                arg = ce.args[0]
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and "d2h" in arg.value
                ):
                    return True
        return False


# --------------------------------------------------------------------------
# DL011 retrace-hazard
# --------------------------------------------------------------------------

# trace-time-structural attribute reads on a traced value (shape/dtype are
# Python objects under tracing — branching on them specializes, it does
# not fail; any OTHER use of the value in a Python branch does)
_STRUCTURAL_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding"})
# calls that probe the PYTREE STRUCTURE of their argument (Python type /
# arity), which is static under tracing — `if is_quant(cache):` picks the
# QuantPool vs array form of the program, it never reads traced data
_STRUCTURAL_CALLS = frozenset({"len", "isinstance", "type", "is_quant"})


class RetraceHazard:
    """DL011: per-call-varying values where jit expects trace constants.

    Two shapes:

      * data-dependent Python branching inside a jit-wrapped body — an
        ``if``/``while`` on a traced parameter's *value* raises
        TracerBoolConversionError at best; at worst the branch happens to
        work at trace time and silently bakes one side in;
      * a call site feeding a per-call-varying expression (``len(...)``,
        ``.shape[...]``, arithmetic) to a ``static_argnames`` parameter —
        every distinct value is a full retrace + XLA compile on the hot
        path (the repo buckets these: cfg.bucket_for / padded shapes).
    """

    id = "DL011"
    name = "retrace-hazard"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        project = ctx.project
        if project is None:
            return
        yield from self._check_traced_branches(ctx, project)
        yield from self._check_static_callsites(ctx, project)

    def _check_traced_branches(self, ctx, project) -> Iterable[Finding]:
        for (path, _name), jit in sorted(project.jits.items()):
            fn = jit.wrapped_fn
            if path != ctx.path or fn is None or fn.path != ctx.path:
                continue
            static = set(jit.static_argnames or ())
            for i in jit.static_argnums or ():
                if i < len(fn.params):
                    static.add(fn.params[i])
            traced = {
                p for p in fn.params if p not in static and p != "self"
            }
            for node in ast.walk(fn.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                hit = self._traced_value_use(node.test, traced)
                if hit is None:
                    continue
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"Python branch on traced parameter {hit!r} "
                            f"inside jit-wrapped {fn.name!r} — traced "
                            "values have no Python truth value; this "
                            "either crashes at trace time or silently "
                            "bakes one side into the compiled program",
                    hint="use jnp.where/lax.cond on the traced value, or "
                         f"declare {hit!r} in static_argnames (then bucket "
                         "its values to bound retraces)",
                    context=fn.qualname,
                    detail=f"branch:{fn.qualname}:{hit}",
                )

    @staticmethod
    def _traced_value_use(test: ast.AST, traced: set[str]) -> str | None:
        for n in ast.walk(test):
            if not (isinstance(n, ast.Name) and n.id in traced
                    and isinstance(n.ctx, ast.Load)):
                continue
            parent = getattr(n, "_dl_parent", None)
            if (
                isinstance(parent, ast.Attribute)
                and parent.attr in _STRUCTURAL_ATTRS
            ):
                continue  # x.shape / x.dtype: static under tracing
            if isinstance(parent, ast.Call) and _last(
                dotted(parent.func)
            ) in _STRUCTURAL_CALLS:
                continue  # len(x) / is_quant(x) / isinstance: structural
            if isinstance(parent, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot))
                for op in parent.ops
            ):
                continue  # `x is None`: pytree-structure check, static
            return n.id
        return None

    def _check_static_callsites(self, ctx, project) -> Iterable[Finding]:
        for info in project.functions.values():
            if info.path != ctx.path:
                continue
            for name, call in info.calls:
                jits = project.jit_names.get(_last(name))
                if not jits:
                    continue
                statics = {j.static_argnames for j in jits}
                if len(statics) != 1:
                    continue  # same name, different signatures: stay quiet
                static_names = statics.pop() or ()
                for kw in call.keywords:
                    if kw.arg not in static_names:
                        continue
                    how = self._varying(kw.value)
                    if how is None:
                        continue
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=call.lineno, col=call.col_offset,
                        message=f"static arg {kw.arg!r} of jitted "
                                f"{_last(name)!r} fed a per-call-varying "
                                f"expression ({how}) — every distinct "
                                "value is a full retrace + XLA compile",
                        hint="bucket the value (cfg.bucket_for / pad to a "
                             "fixed set) or make the parameter traced",
                        context=info.qualname,
                        detail=f"static:{info.qualname}:{kw.arg}",
                    )

    @staticmethod
    def _varying(expr: ast.AST) -> str | None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and _last(dotted(n.func)) == "len":
                return "len(...)"
            if isinstance(n, ast.Attribute) and n.attr == "shape":
                return ".shape"
            if isinstance(n, ast.BinOp):
                return "arithmetic"
        return None


# --------------------------------------------------------------------------
# DL012 donation-audit
# --------------------------------------------------------------------------

# parameter names that carry a KV pool / latent cache — the multi-GiB
# buffers where donation is the difference between in-place updates and a
# second full copy in HBM every step
_POOL_PARAMS = frozenset({
    "k_pages", "v_pages", "kv_pages", "latent_pages", "kv_latent",
})


class DonationAudit:
    """DL012: donated buffers read after the call; pool buffers undonated.

    ``donate_argnums`` hands the buffer's memory to XLA: the caller's
    reference is invalid the moment the call is issued — reading it
    afterwards returns garbage (or crashes with buffer-deleted, backend
    depending). The repo idiom rebinds in the same statement
    (``self.k_pages, self.v_pages = fam.decode_steps(..., self.k_pages,
    self.v_pages, ...)``), which is safe and what the rule checks for.

    The registry-level check is the flip side: a jit whose signature
    takes a pool-sized buffer (k_pages/v_pages/latent) WITHOUT donating
    it forces XLA to keep input and output alive simultaneously — the
    pool's HBM footprint doubles for the step. Read-only gathers
    (extract_kv_pages) are legitimate and get a reasoned suppression:
    the contract is written down at the jit definition.
    """

    id = "DL012"
    name = "donation-audit"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        project = ctx.project
        if project is None:
            return
        yield from self._check_undonated_pools(ctx, project)
        for info in project.functions.values():
            if info.path != ctx.path:
                continue
            yield from self._check_read_after_donate(ctx, project, info)

    def _check_undonated_pools(self, ctx, project) -> Iterable[Finding]:
        for (path, _name), jit in sorted(project.jits.items()):
            if path != ctx.path or jit.wrapped_fn is None:
                continue
            donated = set(jit.donate_argnums or ())
            undonated = [
                p for i, p in enumerate(jit.wrapped_fn.params)
                if p in _POOL_PARAMS and i not in donated
            ]
            if not undonated:
                continue
            yield Finding(
                rule=self.id, path=ctx.path,
                line=jit.line, col=jit.col,
                message=f"jit {jit.name!r} takes pool buffer(s) "
                        f"{', '.join(undonated)} without donate_argnums — "
                        "XLA keeps input AND output alive, doubling the "
                        "pool's HBM footprint for the call",
                hint="donate the pool positions (and rebind from the "
                     "result), or suppress with the read-only contract "
                     "as the reason",
                context=jit.context,
                detail=f"undonated:{jit.name}:{','.join(undonated)}",
            )

    def _check_read_after_donate(self, ctx, project, info) -> Iterable[Finding]:
        for name, call in info.calls:
            jits = project.jit_names.get(_last(name))
            if not jits:
                continue
            donates = {j.donate_argnums for j in jits}
            if len(donates) != 1:
                continue
            donate = donates.pop()
            if not donate:
                continue
            rebound = self._stmt_targets(call)
            for pos in donate:
                if pos >= len(call.args):
                    continue
                d = dotted(call.args[pos])
                if d is None or d in rebound:
                    continue
                line = self._first_read_after(info, call, d)
                if line is None:
                    continue
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=call.lineno, col=call.col_offset,
                    message=f"{d} is donated to {_last(name)}() (arg "
                            f"{pos}) but read again at line {line} — the "
                            "buffer is invalid the moment the call is "
                            "issued",
                    hint="rebind the name from the call's result in the "
                         "same statement, or stop donating the position",
                    context=info.qualname,
                    detail=f"donated-read:{info.qualname}:{d}:{pos}",
                )

    @staticmethod
    def _stmt_targets(call: ast.Call) -> set[str]:
        """Dotted names the call's enclosing assignment rebinds —
        donated-and-rebound in one statement is the safe idiom."""
        out: set[str] = set()
        for p in parents(call):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                break
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    elts = (
                        t.elts if isinstance(t, (ast.Tuple, ast.List))
                        else [t]
                    )
                    for el in elts:
                        d = dotted(el)
                        if d:
                            out.add(d)
                break
        return out

    @staticmethod
    def _first_read_after(info, call, name: str) -> int | None:
        """Line of the first use of ``name`` after the call, when that
        use is a read (a rebind first makes later reads fine)."""
        after = getattr(call, "end_lineno", call.lineno)
        first: tuple[int, int, bool] | None = None  # (line, col, is_load)
        for n in ast.walk(info.node):
            if isinstance(n, (ast.Name, ast.Attribute)):
                if dotted(n) != name or n.lineno <= after:
                    continue
                key = (n.lineno, n.col_offset, isinstance(n.ctx, ast.Load))
                if first is None or key[:2] < first[:2]:
                    first = key
        if first is not None and first[2]:
            return first[0]
        return None


# --------------------------------------------------------------------------
# DL013 spec-coverage
# --------------------------------------------------------------------------


class SpecCoverage:
    """DL013: shard_map/pjit specs that don't cover the declared params.

    Two checks:

      * arity — ``in_specs`` entries vs the wrapped callable's positional
        params (and ``out_specs`` vs its visible return arity): a missing
        entry fails at the first real mesh, which on a CPU-tested repo
        means production;
      * pytree-leaf coverage — a quant-capable value (one the enclosing
        function tests with ``is_quant(...)``) passed into a shard_map
        whose spec for that position is a bare ``P(...)``: a QuantPool's
        scale leaves have no spec, so the mapped kernel can't accept the
        quantized form at all — the generalized ROADMAP #7 scale-leaf
        bug. Either plumb per-leaf specs or guard the path AND account
        for the fallback (DL014).
    """

    id = "DL013"
    name = "spec-coverage"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        project = ctx.project
        if project is None:
            return
        for sm in project.shard_maps:
            if sm.path != ctx.path:
                continue
            yield from self._check_arity(ctx, project, sm)
            yield from self._check_quant_leaves(ctx, project, sm)

    # -- arity --------------------------------------------------------------

    def _check_arity(self, ctx, project, sm) -> Iterable[Finding]:
        n_params = self._wrapped_param_count(project, sm)
        specs = self._spec_elements(sm)
        if n_params is not None and specs is not None:
            n_specs, exact = specs
            if (exact and n_specs != n_params) or (
                not exact and n_specs > n_params
            ):
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=sm.line, col=sm.col,
                    message=f"shard_map declares {n_specs} in_specs "
                            f"{'=' if exact else '>'}"
                            f" for a callable taking {n_params} params — "
                            "every positional arg needs exactly one spec "
                            "entry",
                    hint="add/remove the spec entry; None (replicated) "
                         "is an explicit choice, not a default",
                    context=sm.context,
                    detail=f"arity:{sm.context}:{n_specs}:{n_params}",
                )
        n_out = self._out_spec_count(sm)
        n_ret = self._wrapped_return_arity(project, sm)
        if n_out is not None and n_ret is not None and n_out != n_ret:
            yield Finding(
                rule=self.id, path=ctx.path,
                line=sm.line, col=sm.col,
                message=f"shard_map declares {n_out} out_specs for a "
                        f"callable returning {n_ret} values",
                hint="one out_spec per returned leaf",
                context=sm.context,
                detail=f"out-arity:{sm.context}:{n_out}:{n_ret}",
            )

    @staticmethod
    def _wrapped_param_count(project, sm) -> int | None:
        w = sm.wrapped
        if isinstance(w, ast.Lambda):
            a = w.args
            return len(a.posonlyargs) + len(a.args)
        if isinstance(w, ast.Name):
            cands = [
                f for f in project.by_name.get(w.id, ())
                if f.path == sm.path
            ] or project.by_name.get(w.id, [])
            if len(cands) == 1:
                return len([p for p in cands[0].params if p != "self"])
        return None

    @staticmethod
    def _wrapped_return_arity(project, sm) -> int | None:
        w = sm.wrapped
        node = None
        if isinstance(w, ast.Lambda):
            node = w.body
            return len(node.elts) if isinstance(node, ast.Tuple) else None
        if isinstance(w, ast.Name):
            cands = [
                f for f in project.by_name.get(w.id, ())
                if f.path == sm.path
            ] or project.by_name.get(w.id, [])
            if len(cands) != 1:
                return None
            arities = set()
            for n in ast.walk(cands[0].node):
                if isinstance(n, ast.Return) and n.value is not None:
                    arities.add(
                        len(n.value.elts)
                        if isinstance(n.value, ast.Tuple) else 1
                    )
            if len(arities) == 1:
                a = arities.pop()
                return a if a > 1 else None  # single value: can't misdeclare
        return None

    def _spec_elements(self, sm) -> tuple[int, bool] | None:
        """(entry count, exact?) of in_specs. Handles the repo idiom of a
        locally-built list (``in_specs = [...]; ... in_specs.append(...);
        shard_map(..., in_specs=tuple(in_specs))``): the literal base
        count is a lower bound (exact=False) once an append is seen."""
        return self._count_spec_expr(sm, sm.in_specs)

    def _out_spec_count(self, sm) -> int | None:
        counted = self._count_spec_expr(sm, sm.out_specs)
        if counted is None or not counted[1]:
            return None
        n, _ = counted
        return n

    @staticmethod
    def _count_spec_expr(sm, expr) -> tuple[int, bool] | None:
        if expr is None:
            return None
        if isinstance(expr, (ast.Tuple, ast.List)):
            return len(expr.elts), True
        if isinstance(expr, ast.Call) and _last(dotted(expr.func)) in (
            "P", "PartitionSpec"
        ):
            return 1, True
        if (
            isinstance(expr, ast.Call)
            and _last(dotted(expr.func)) == "tuple"
            and expr.args
            and isinstance(expr.args[0], ast.Name)
        ):
            # tuple(name): find the local list literal + appends
            var = expr.args[0].id
            fn = enclosing_function(sm.node)
            if fn is None:
                return None
            base: int | None = None
            appended = False
            for n in ast.walk(fn):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and n.targets[0].id == var
                    and isinstance(n.value, (ast.List, ast.Tuple))
                ):
                    base = len(n.value.elts)
                elif (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("append", "extend")
                    and dotted(n.func.value) == var
                ):
                    appended = True
            if base is not None:
                return base, not appended
        return None

    # -- quant pytree leaves ------------------------------------------------

    def _check_quant_leaves(self, ctx, project, sm) -> Iterable[Finding]:
        fn = enclosing_function(sm.node)
        if fn is None:
            return
        quant_names = {
            dotted(n.args[0])
            for n in ast.walk(fn)
            if isinstance(n, ast.Call) and n.args
            and _last(dotted(n.func)) == "is_quant"
        } - {None}
        if not quant_names:
            return
        arg_names = self._kernel_args(fn, sm)
        if arg_names is None:
            return
        spec_elts = self._spec_expr_elts(fn, sm)
        for i, arg in enumerate(arg_names):
            if arg not in quant_names:
                continue
            if spec_elts is not None and i < len(spec_elts):
                el = spec_elts[i]
                if not (
                    isinstance(el, ast.Call)
                    and _last(dotted(el.func)) in ("P", "PartitionSpec")
                ):
                    continue  # nested/helper spec: leaves are covered
            yield Finding(
                rule=self.id, path=ctx.path,
                line=sm.line, col=sm.col,
                message=f"quant-capable {arg!r} (this function tests "
                        f"is_quant({arg})) enters shard_map under an "
                        "array-only P(...) spec — a QuantPool's scale "
                        "leaves have no PartitionSpec, so the mapped "
                        "kernel cannot take the quantized form",
                hint="plumb per-leaf specs for the pool pytree, or guard "
                     "the quantized case out AND account for the "
                     "fallback (ops.fallback.note_fallback — DL014)",
                context=sm.context,
                detail=f"quant-leaf:{sm.context}:{arg}",
            )

    @staticmethod
    def _kernel_args(fn, sm) -> list[str | None] | None:
        """Positional arg names at the mapped kernel's invocation:
        ``kernel = shard_map(kernel, ...); ... kernel(*args)`` with
        ``args = (...)``, or a direct ``kernel(a, b, c)``."""
        target: str | None = None
        for p in parents(sm.node):
            if isinstance(p, ast.Assign) and len(p.targets) == 1 and (
                isinstance(p.targets[0], ast.Name)
            ):
                target = p.targets[0].id
                break
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        if target is None:
            return None
        tuples: dict[str, list[str | None]] = {}
        for n in ast.walk(fn):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Tuple)
            ):
                tuples[n.targets[0].id] = [
                    dotted(e) for e in n.value.elts
                ]
        for n in ast.walk(fn):
            if not (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == target
                and n is not sm.node
            ):
                continue
            if (
                len(n.args) == 1
                and isinstance(n.args[0], ast.Starred)
                and isinstance(n.args[0].value, ast.Name)
            ):
                return tuples.get(n.args[0].value.id)
            if n.args and not any(
                isinstance(a, ast.Starred) for a in n.args
            ):
                return [dotted(a) for a in n.args]
        return None

    def _spec_expr_elts(self, fn, sm) -> list[ast.AST] | None:
        expr = sm.in_specs
        if isinstance(expr, (ast.Tuple, ast.List)):
            return list(expr.elts)
        if (
            isinstance(expr, ast.Call)
            and _last(dotted(expr.func)) == "tuple"
            and expr.args
            and isinstance(expr.args[0], ast.Name)
        ):
            var = expr.args[0].id
            for n in ast.walk(fn):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and n.targets[0].id == var
                    and isinstance(n.value, (ast.List, ast.Tuple))
                ):
                    return list(n.value.elts)
        return None


# --------------------------------------------------------------------------
# DL014 silent-fallback guard
# --------------------------------------------------------------------------

_NOTERS = frozenset({"note_fallback"})


class SilentFallback:
    """DL014: a capability-gated downgrade that accounts for nothing.

    The shape: a gate built from catalogued capability probes
    (catalog.FALLBACK_GATES — use_pallas / use_fused_decode /
    lane_aligned), a fast path behind ``if gate:``, and a fallthrough or
    ``else`` that quietly takes the slow path. ROADMAP #7's "fp8 + tp>1
    silently takes the XLA path" shipped exactly like this: correct
    output, 0.358x the throughput, zero signal. The downgrade branch
    must call ``ops.fallback.note_fallback(reason)`` (one-shot warning +
    dynamo_fused_fallback_total{reason}) or at least log — then the
    downgrade is a dashboard fact instead of a benchmark surprise.
    """

    id = "DL014"
    name = "silent-fallback"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        gates = set(getattr(ctx.catalog, "FALLBACK_GATES", ()) or ())
        if not gates:
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.If):
                continue
            gate = self._gate_of(node, gates)
            if gate is None:
                continue
            region = self._fallback_region(node)
            if not region or self._accounted(region):
                continue
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"capability gate {gate}() downgrades to a "
                        "fallback path that neither counts nor logs "
                        "itself — the slow path ships invisibly "
                        "(the ROADMAP #7 fp8+tp>1 XLA-fallback class)",
                hint="call dynamo_tpu.ops.fallback.note_fallback("
                     "\"<reason>\") in the fallback branch (one-shot "
                     "warning + dynamo_fused_fallback_total{reason})",
                context=qualname(node),
                detail=f"silent-fallback:{qualname(node)}:{gate}",
            )

    @staticmethod
    def _gate_of(node: ast.If, gates: set[str]) -> str | None:
        """Gate name when the test (or the local boolean it was assigned
        from) contains a catalogued capability-probe call."""
        exprs = [node.test]
        if isinstance(node.test, ast.Name) or (
            isinstance(node.test, ast.UnaryOp)
            and isinstance(node.test.op, ast.Not)
            and isinstance(node.test.operand, ast.Name)
        ):
            var = (
                node.test.id if isinstance(node.test, ast.Name)
                else node.test.operand.id
            )
            fn = enclosing_function(node)
            scope = fn if fn is not None else None
            if scope is not None:
                for n in ast.walk(scope):
                    if (
                        isinstance(n, ast.Assign)
                        and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)
                        and n.targets[0].id == var
                    ):
                        exprs.append(n.value)
        for expr in exprs:
            for n in ast.walk(expr):
                if isinstance(n, ast.Call):
                    last = _last(dotted(n.func))
                    if last in gates:
                        return last
        return None

    @staticmethod
    def _fallback_region(node: ast.If) -> list[ast.stmt] | None:
        """The statements the downgrade takes. ``if not gate:`` puts the
        fallback in the body; ``if gate:`` puts it in the else, or — when
        the fast body returns — in the remainder of the parent block."""
        if isinstance(node.test, ast.UnaryOp) and isinstance(
            node.test.op, ast.Not
        ):
            return node.body
        if node.orelse:
            return node.orelse
        if not any(isinstance(s, ast.Return) for s in node.body):
            return None  # no clear fast/slow split: stay quiet
        parent = getattr(node, "_dl_parent", None)
        body = getattr(parent, "body", None)
        if isinstance(body, list) and node in body:
            return body[body.index(node) + 1:]
        return None

    @staticmethod
    def _accounted(region: list[ast.stmt]) -> bool:
        for stmt in region:
            for n in ast.walk(stmt):
                if not isinstance(n, ast.Call):
                    continue
                d = dotted(n.func) or ""
                last = _last(d)
                if last in _NOTERS:
                    return True
                recv = d.rsplit(".", 1)[0] if "." in d else ""
                if last in _LOG_METHODS and (
                    "log" in recv.lower() or recv == "logging"
                ):
                    return True
                if d == "warnings.warn":
                    return True
        return False


# --------------------------------------------------------------------------
# DL015 lock-discipline
# --------------------------------------------------------------------------


class LockDiscipline:
    """DL015: threading locks across await; lock-order inversion.

    Two checks over the whole project index:

      * a *sync* ``with <lock>:`` whose body awaits, inside an ``async
        def`` — a threading.Lock held across a suspension point blocks
        every OTHER event-loop coroutine AND every thread contending the
        lock for as long as the awaited thing takes; under kill-9 churn
        that's the step-thread/asyncio deadlock shape;
      * interprocedural lock-order inversion — function F takes lock A
        then (directly or via resolvable callees) lock B, while G takes
        B then A. Lock identity is ``Class.attr`` for ``self.X``
        receivers and ``path:name`` for module globals; callee
        resolution is single-candidate only (precision over recall — a
        false inversion report would train people to ignore the rule).
    """

    id = "DL015"
    name = "lock-discipline"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        return ()  # project-level rule: see check_project

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        for ctx in project.contexts:
            yield from self._check_sync_lock_across_await(ctx)
        yield from self._check_lock_order(project)

    # -- (a) sync lock across await ----------------------------------------

    def _check_sync_lock_across_await(self, ctx) -> Iterable[Finding]:
        for node in ctx.nodes:
            if not isinstance(node, ast.With):
                continue
            fn = enclosing_function(node)
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            lock_src = self._lock_src(node)
            if lock_src is None:
                continue
            aw = next(
                (
                    n for stmt in node.body for n in ast.walk(stmt)
                    if isinstance(n, (ast.Await, ast.AsyncFor,
                                      ast.AsyncWith))
                ),
                None,
            )
            if aw is None:
                continue
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"sync `with {lock_src}:` holds a threading lock "
                        f"across an await (line {aw.lineno}) — the loop "
                        "suspends with the lock held, stalling every "
                        "contending thread AND coroutine for the full "
                        "await",
                hint="use asyncio.Lock for loop-side critical sections, "
                     "or snapshot under the lock and await after release",
                context=qualname(node),
                detail=f"lock-await:{qualname(node)}:{lock_src}",
            )

    @staticmethod
    def _lock_src(node) -> str | None:
        for item in node.items:
            try:
                src = ast.unparse(item.context_expr)
            # dynalint: disable=DL003 -- defensive: an unparse failure
            # just means "not a lock expr"; nothing to report
            except Exception:  # pragma: no cover - defensive
                continue
            if "lock" in src.lower() and "_phase" not in src:
                return src
        return None

    # -- (b) lock-order inversion ------------------------------------------

    def _check_lock_order(self, project) -> Iterable[Finding]:
        # per-function: direct acquisitions (lock id -> With node) and the
        # transitive closure of locks acquired anywhere inside
        direct: dict[tuple[str, str], list[tuple[str, ast.AST]]] = {}
        for key, info in project.functions.items():
            direct[key] = [
                (lid, w) for lid, w in self._acquisitions(info)
            ]
        closure: dict[tuple[str, str], set[str]] = {
            key: {lid for lid, _ in acqs} for key, acqs in direct.items()
        }
        changed = True
        while changed:
            changed = False
            for key, info in project.functions.items():
                for name, _call in info.calls:
                    cands = project._resolve(info, name)
                    if len(cands) != 1:
                        continue  # precision: only unambiguous callees
                    k2 = (cands[0].path, cands[0].qualname)
                    extra = closure.get(k2, set()) - closure[key]
                    if extra:
                        closure[key] |= extra
                        changed = True
        # edges: lock A held (With span) while lock B is acquired inside —
        # directly nested or via a resolvable call
        edges: dict[tuple[str, str], list] = {}

        def note(a: str, b: str, info, node) -> None:
            if a != b:
                edges.setdefault((a, b), []).append((info, node))

        for key, info in project.functions.items():
            for lid, w in direct[key]:
                for stmt in w.body:
                    for n in ast.walk(stmt):
                        if isinstance(n, (ast.With, ast.AsyncWith)):
                            for lid2, w2 in self._acquisitions_of(info, n):
                                note(lid, lid2, info, w2)
                        elif isinstance(n, ast.Call):
                            name = dotted(n.func)
                            if not name:
                                continue
                            cands = project._resolve(info, name)
                            if len(cands) != 1:
                                continue
                            k2 = (cands[0].path, cands[0].qualname)
                            for lid2 in closure.get(k2, ()):
                                note(lid, lid2, info, n)
        for (a, b), sites in sorted(edges.items()):
            if (b, a) not in edges:
                continue
            info, node = sites[0]
            other_info, other_node = edges[(b, a)][0]
            yield Finding(
                rule=self.id, path=info.path,
                line=node.lineno, col=node.col_offset,
                message=f"lock-order inversion: {info.qualname} takes "
                        f"{a} then {b}, while {other_info.qualname} "
                        f"({other_info.path}:{other_node.lineno}) takes "
                        f"{b} then {a} — two contenders deadlock",
                hint="pick one global order for the two locks and "
                     "restructure the second site (or collapse to one "
                     "lock)",
                context=info.qualname,
                detail=f"inversion:{a}->{b}",
            )

    def _acquisitions(self, info) -> list[tuple[str, ast.AST]]:
        """(lock id, With node) pairs acquired directly by this function."""
        out: list[tuple[str, ast.AST]] = []
        for n in ast.walk(info.node):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                out.extend(self._acquisitions_of(info, n))
        return out

    def _acquisitions_of(self, info, node) -> list[tuple[str, ast.AST]]:
        out = []
        for item in node.items:
            d = dotted(item.context_expr)
            if d is None or "lock" not in d.lower():
                continue
            out.append((self._lock_id(info, d), node))
        return out

    @staticmethod
    def _lock_id(info, d: str) -> str:
        if d.startswith("self.") and info.cls:
            return f"{info.cls}.{d[5:]}"
        if "." in d:
            return f"{info.path}:{d}"
        return f"{info.path}:{d}"
