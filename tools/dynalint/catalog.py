"""The reviewable registries DL006 checks against.

Adding a fault site or metric is a two-line diff *here* plus the code —
which is the point: the catalog shows up in review, chaos schedules and
dashboards reference these exact strings, and dynalint fails on drift in
either direction (unknown name used, or catalogued name unused).

``FAULT_SITES`` mirrors ``dynamo_tpu.runtime.faults.KNOWN_SITES`` — the
runtime complement that warns when a ``DYN_FAULTS`` spec names a site no
code declares. tests/test_static_analysis.py asserts the two sets match.
"""

from __future__ import annotations

# site -> where it fires / what failure it simulates
FAULT_SITES: dict[str, str] = {
    "transport.connect": "runtime/transport.py dial — peer unreachable",
    "transport.send": "runtime/transport.py request send — cut connection",
    "transport.recv": "runtime/transport.py rx loop — channel dies mid-stream",
    "transport.partition": "runtime/hub_replica.py replica links — "
                           "address-pair-scoped partition (drop=A|B "
                           "symmetric, A>B one-way): refuses dials, kills "
                           "sync streams, eats follower acks",
    "hub.dial": "runtime/hub_client.py connect — hub unreachable",
    "hub.call": "runtime/hub_client.py RPC — lossy hub link",
    "hub.wal_append": "runtime/hub_store.py WAL append — disk write fails",
    "hub.fsync": "runtime/hub_store.py per-append fsync — slow/failing "
                 "durable disk on the mutation path",
    "hub.snap_fsync": "runtime/hub_store.py snapshot fsync — compaction "
                      "failure (counted, survived on the uncompacted WAL)",
    "engine.step": "engine/core.py step thread — device step fails/stalls",
    "engine.admit": "engine/core.py admission — worker vanishes pre-admit",
    "engine.compile": "engine/core.py precompile — slow/failing shape "
                      "warmup (serving must come up and eat the compile "
                      "at first use)",
    "engine.spec_verify": "engine/core.py speculative verify — dispatch "
                          "failure must fall back to non-spec decode for "
                          "the affected slots (pages rolled back, no "
                          "client-visible error)",
    "engine.guided_compile": "guided/runtime.py grammar compile — a "
                             "failing grammar->mask compile must bounce "
                             "the request as a typed 400 (no slot, no "
                             "page, counter trip), never wedge a stream",
    "engine.quant": "engine/core.py quantized-onboard validation — "
                    "corrupt fp8 tier block (bad scale bytes): must be "
                    "treated as a tier miss + re-prefill, never a "
                    "NaN-poisoned page",
    "engine.preempt": "engine/core.py priority preemption — an injected "
                      "error SKIPS the preemption (the interactive "
                      "request waits; the batch victim keeps running): "
                      "serving degrades, never breaks, and page "
                      "accounting stays clean",
    "epp.breaker": "gateway/epp.py pick path — an injected error records "
                   "a FAILURE outcome against the picked instance, so "
                   "chaos schedules can drive a breaker through "
                   "eject -> half-open -> recovery without a genuinely "
                   "sick worker",
    "disagg.pull": "disagg/transfer.py KV pull — transfer plane failure",
    "kvbm.onboard": "kvbm/pool.py + manager.py tier block on receipt — "
                    "silent bit flips in offloaded KV (corrupt action): "
                    "checksum must catch it as a tier miss, never decode "
                    "a poisoned page",
    "migration.resume": "runtime/integrity.py resume-prompt intake — "
                        "corrupt the migrated token ids on the wire: the "
                        "checksum mismatch must re-drive the migration, "
                        "never prefill a poisoned prompt",
    "health.canary": "runtime/health.py SDC canary — corrupt the "
                     "known-answer probe's output tokens: the golden "
                     "mismatch must quarantine the worker "
                     "(dynamo_worker_quarantines_total{reason=\"sdc\"})",
}

# engine step-thread profiler phase names (engine/core.py _phase /
# _prof_add / profile_snapshot) -> meaning. DL006-style registry for the
# SAME reason as METRIC_NAMES: benchmarks/profile_engine.py's
# attribution sections, bench.py's dispatch_overhead_frac, and the
# dashboards built on profile snapshots reference these exact strings —
# a renamed phase silently zeroes every consumer. Two-way sync with the
# code is test-enforced (tests/test_dispatch_profile.py).
PROFILE_PHASES: dict[str, str] = {
    "idle": "step thread parked waiting for work",
    "spmd_sync": "rejoining follower state-sync service",
    "materialize": "async admission-wave first-token landings",
    "flush": "pipeline flush before cancels/admin ops",
    "admit_loop": "admission dequeue + page acquisition",
    "packed_prefill": "packed prefill dispatch(es) for the step",
    "complete_admissions": "first-token sample + emit for admissions",
    "eager_readmit": "same-cycle re-admission pass after a burst freed slots",
    "readmit_wait": "bounded wait for a closed-loop resubmission",
    "build_batch": "host-side burst assembly",
    "dispatch": "decode burst dispatch (host issue time)",
    "process": "burst processing (stop semantics, seal, stream)",
    "process.d2h_sync": "burst token download sync inside process",
    "readmit.admit_wait": "generate() enqueue -> step-thread dequeue",
    "readmit.prefill_dispatch": "dequeue -> prefill+sample dispatched",
    "readmit.first_token": "dispatch complete -> first token streamed",
    "dispatch.d2h_wait": "step thread blocked on device->host transfers "
                         "(outside admission phases)",
    "readmit.d2h_wait": "d2h blocks nested inside admission phases "
                        "(sync-admission device_get, aged wave "
                        "materialization) — already inside the readmit "
                        "phase sums",
    "dispatch.dispatches": "jitted device programs issued (count)",
    "dispatch.compile": "backend compile events since engine build",
    "spec.draft": "prompt-lookup drafting over spec-managed slots",
    "spec.verify": "packed speculative-verify dispatch + target sync",
    "spec.rollback": "page release of rejected draft tails (and the "
                     "injected-verify-failure fallback)",
    "guided.mask": "host-side [B, V] allowed-mask assembly for "
                   "constrained slots (burst + admission sampling)",
    "guided.lookahead": "scratch-cursor draft walk for guided x spec "
                        "verify (per-position masks, no state mutation)",
    "preempt": "priority preemption: pipeline flush + seal/offload + "
               "resume-request rebuild for one paused batch stream",
}

# span name (runtime/tracing.py span()/emit_span()) -> what it times.
# Same two-way discipline as FAULT_SITES/METRIC_NAMES (DL006): a span
# name not catalogued here fails the scan (dashboards and the e2e trace
# tests reference these exact strings), and a catalogued name no code
# emits warns as stale. tests/test_observability.py asserts the whole
# catalog is emitted by the instrumented smoke path.
SPAN_NAMES: dict[str, str] = {
    "http.request": "frontend route handling, admission -> stream "
                    "complete (chat/completions/responses/embeddings)",
    "http.preprocess": "render + tokenize on the compute pool",
    "epp.pick": "EPP routing decision (tokenize, KV score, resolve)",
    "transport.call": "client-side endpoint call, dispatch -> "
                      "end-of-stream (runtime/component.py)",
    "migration.resume": "backoff wait after a stream death; the "
                        "re-driven attempt is the next transport.call "
                        "span in the same trace (frontend/migration.py)",
    "disagg.pull": "decode-side staging of remote prefill KV",
    "worker.request": "worker-side request lifecycle, enqueue -> "
                      "finish (runtime/flight.py, child of the "
                      "caller's transport.call)",
    "engine.queue_wait": "admission-queue wait, enqueue -> step-thread "
                         "dequeue",
    "engine.prefill": "admit -> first token (prefill chunk count attr)",
    "engine.decode": "first token -> finish, aggregated per request",
    "engine.spec": "speculative-verify activity, first -> last verify",
    "engine.guided_compile": "grammar -> token-mask automaton compile "
                             "(or LRU fetch) before admission "
                             "(engine/core.py generate)",
}

# step-thread / hot-loop roots for the DL010 host-sync analysis, spelled
# "path/suffix.py::Qualified.name". The jit registry ALSO discovers hot
# roots structurally (any ``threading.Thread(target=...)`` entry point);
# this catalog pins the ones the serving SLO actually rides on, so a
# refactor that loses the structural marker still keeps the closure rooted.
HOT_PATH_ROOTS: dict[str, str] = {
    "dynamo_tpu/engine/core.py::InferenceEngine._thread_loop":
        "the engine step thread — owns the device; every unaccounted "
        "host<->device sync here is serial time added to EVERY decode "
        "step (the BENCH_r05 dispatch-overhead gap lives here)",
}

# capability gates whose False branch downgrades a fused/quantized path
# to a slower generic one. DL014 requires the downgrade branch to account
# for itself (ops.fallback.note_fallback / a log call) — ROADMAP #7's
# "fp8 + tp>1 silently takes the XLA path" is the incident class.
FALLBACK_GATES: dict[str, str] = {
    "use_pallas": "ops/attention.py — Pallas kernels enabled "
                  "(DYNAMO_PALLAS / on-TPU default)",
    "use_fused_decode": "ops/attention.py — fused decode-update kernel "
                        "enabled (DYNAMO_FUSED_DECODE)",
    "lane_aligned": "ops/attention.py — pool head dim fills full TPU "
                    "lanes (128); misaligned pools take the XLA path",
    "supports_fused": "generic capability probe spelling",
}

# cross-thread shared state the concurrency tooling tracks, spelled
# "owner.attr". This is the SAME registry as tools/dynarace/registry.py
# SHARED_STATE — dynalint's static DL005 layer and dynarace's dynamic
# happens-before layer must agree on what the cross-thread state IS, so
# the two copies are test-enforced identical (tests/test_dynarace.py,
# the DL006 fault-site discipline). DL005 findings whose attribute
# matches a catalogued suffix cite the entry's documented discipline.
SHARED_STATE: dict[str, str] = {
    "engine.step_times": (
        "engine/core.py step-latency deque — step thread appends, "
        "telemetry sampler (event loop) drains via popleft; GIL-atomic "
        "bounded deque, no lock (suppressed, see suppressions.py)"
    ),
    "engine.burst_fills": (
        "engine/core.py burst-fill deque — same single-appender/"
        "single-drainer deque discipline as engine.step_times"
    ),
    "flight.timeline": (
        "runtime/flight.py timeline ring (events/attrs/retention "
        "buckets) — step thread and event loop both enter; EVERY access "
        "must hold FlightRecorder._lock (flight.lock), including "
        "snapshot reads (the pre-dynarace snapshot-outside-lock race)"
    ),
    "kvbm.checksums": (
        "kvbm/manager.py block-checksum dict — offload thread stamps on "
        "offer, step thread reads on onboard and pops on corruption; "
        "guarded by kvbm.manager.lock (the pre-dynarace unguarded-dict "
        "race)"
    ),
    "hub.capture_log": (
        "runtime/hub_store.py compaction capture list — event-loop-only "
        "mutation; the snapshot worker thread sees state only through "
        "the hub.snapshot to_thread hand-off edge"
    ),
}

# metric name (without the dynamo_ prefix MetricsRegistry adds) -> meaning
METRIC_NAMES: dict[str, str] = {
    "http_requests_total": "HTTP requests by model/route/status",
    "time_to_first_token_seconds": "TTFT histogram by model",
    "inter_token_latency_seconds": "ITL histogram by model",
    "request_duration_seconds": "end-to-end request duration by model",
    "output_tokens_total": "generated tokens by model",
    "input_tokens_total": "prompt tokens by model",
    "requests_completed_total": "requests that reached the backend",
    "inflight_requests": "in-flight request gauge by model",
    "hub_compaction_failures_total": "hub snapshot-compaction failures "
                                     "(serving continues on the "
                                     "uncompacted WAL)",
    "hub_elections_total": "hub replica election rounds by outcome "
                           "(won/lost/pre_lost)",
    "hub_term": "current fencing epoch (election term) per hub replica",
    "hub_redirects_total": "hub client write bounces by reason "
                           "(not_leader | no_quorum | unavailable) — a "
                           "redirect-chase storm during failover is a "
                           "first-class signal, not an inference from "
                           "latency (sim leader-kill scenario asserts "
                           "on it)",
    "hub_backoff_seconds": "seconds the hub client slept between "
                           "redirect hops (server-hinted and "
                           "exponential backoff alike)",
    "spec_tokens_total": "speculative draft tokens by verify outcome "
                         "(accepted | rejected) — the live acceptance "
                         "rate of prompt-lookup decoding",
    "guided_requests_total": "guided-decoding requests by outcome "
                             "(ok | truncated | violation | aborted | "
                             "compile_error | unavailable) — conformance "
                             "delivered vs cut mid-grammar vs bounced at "
                             "the grammar compiler",
    # stream plane (runtime/transport.py, every /metrics surface via the
    # module registry)
    "transport_frames_total": "data-plane frames sent by kind "
                              "(open | data | end | err | cancel) — a "
                              "coalesced data frame counts ONCE however "
                              "many payloads it carries, so frames/token "
                              "< 1 is the coalescing win the STREAM_r0x "
                              "artifacts assert",
    "transport_flush_bytes": "bytes handed to the transport per corked "
                             "flush (batch-size histogram of the "
                             "one-flush-per-tick writer)",
    # EPP pick-path telemetry (gateway/epp.py /metrics)
    "epp_pick_seconds": "EPP pick-path latency histogram",
    # KV-router data plane (kv_router/router.py, on every /metrics
    # surface via the module registry)
    "router_pick_seconds": "KV routing decision latency by phase "
                           "(hash | overlap | select) — the per-pick "
                           "attribution the ROUTER_r0x artifacts and "
                           "router panels read",
    "router_shard_id": "prefix-hash shard this router process serves "
                       "(0-based; 0 when unsharded) — joins a shard's "
                       "metrics to its slice of the shard map",
    "epp_cache_lookups_total": "EPP prefix-cache lookups by cache "
                               "(cards | instances) and outcome "
                               "(hit | miss)",
    # worker telemetry registry (engine/telemetry.py, on every /metrics
    # surface incl. the worker status server)
    "engine_step_seconds": "engine step-thread cycle latency histogram "
                           "(work cycles only)",
    "engine_burst_tokens": "tokens landed per processed decode burst",
    "engine_pages": "KV page pool gauge by state "
                    "(active | cached | free)",
    "engine_slots_active": "decode slots currently running",
    "engine_batch_occupancy": "active slots / max_decode_slots (0..1)",
    "engine_waiting_requests": "admission queue depth",
    "engine_dispatches_total": "jitted device programs issued",
    "engine_admission_rejects_total": "requests refused at admission by "
                                      "reason (draining | saturated | "
                                      "deadline) — the 503/504 feeders",
    "engine_dispatch_overhead_frac": "step-thread d2h-blocked fraction "
                                     "of the sample window (0 unless "
                                     "DYNAMO_ENGINE_PROFILE=1)",
    "engine_spec_acceptance_rate": "cumulative speculative-draft "
                                   "acceptance rate",
    # fused-kernel fallback accounting (ops/fallback.py, on every
    # /metrics surface via the module registry)
    "fused_fallback_total": "fused/quantized fast-path downgrades by "
                            "reason (quant_tp_shardmap | lane_misaligned "
                            "| no_pallas_backend | fused_decode_disabled) "
                            "— counted at TRACE time, so each compiled "
                            "specialization bumps it once, not once per "
                            "step; nonzero quant_tp_shardmap on a TP>1 "
                            "fp8 deployment is the ROADMAP #7 silent "
                            "XLA-path regression made visible",
    "kvbm_tier_bytes": "KVBM tier footprint gauge by tier "
                       "(host | disk | remote) — quantized blocks "
                       "(kv_dtype=fp8) land at packed fp8+scale width, "
                       "so the tier halving vs bf16 is observable here",
    # overload-control plane (engine/tenancy.py + gateway/breaker.py)
    "engine_preemptions_total": "batch streams paused to the host tier "
                                "by reason (interactive_admission | "
                                "interactive_pages) — the priority-"
                                "preemption activity counter",
    "tenant_tokens_total": "admission-charged token cost by tenant and "
                           "outcome (admitted | rejected | shed) — "
                           "rejected feeds the 429s, shed the "
                           "overload-policy bounces",
    "epp_breaker_state": "per-instance circuit-breaker state gauge "
                         "(0 closed, 1 half-open, 2 open) — a sick "
                         "worker browning out is visible AS a brownout",
    # closed-loop SLA autoscaler (autoscaler/metrics.py, on the /metrics
    # surface of whatever process hosts the controller)
    "autoscaler_plan_revisions_total": "ScalePlans emitted (each revision "
                                       "is one actuated fleet change)",
    "autoscaler_actuation_seconds": "backend.apply latency histogram — "
                                    "plan emission to acknowledged "
                                    "actuation",
    "autoscaler_replicas_desired": "latest plan's target per dimension "
                                   "(workers | prefill | router_shards)",
    "autoscaler_replicas_actual": "backend-observed replicas per "
                                  "dimension — desired vs actual gap is "
                                  "the convergence debt",
    "autoscaler_predictor_error": "matured forecast error (predicted - "
                                  "observed demand) at the pre-scale "
                                  "horizon; systematic bias here means "
                                  "the predictor is mis-tuned",
    "autoscaler_convergence_ticks": "ticks from plan emission until "
                                    "observed counts matched it",
}
