"""The reviewable registries DL006 checks against.

Adding a fault site or metric is a two-line diff *here* plus the code —
which is the point: the catalog shows up in review, chaos schedules and
dashboards reference these exact strings, and dynalint fails on drift in
either direction (unknown name used, or catalogued name unused).

``FAULT_SITES`` mirrors ``dynamo_tpu.runtime.faults.KNOWN_SITES`` — the
runtime complement that warns when a ``DYN_FAULTS`` spec names a site no
code declares. tests/test_static_analysis.py asserts the two sets match.
"""

from __future__ import annotations

# site -> where it fires / what failure it simulates
FAULT_SITES: dict[str, str] = {
    "transport.connect": "runtime/transport.py dial — peer unreachable",
    "transport.send": "runtime/transport.py request send — cut connection",
    "transport.recv": "runtime/transport.py rx loop — channel dies mid-stream",
    "hub.dial": "runtime/hub_client.py connect — hub unreachable",
    "hub.call": "runtime/hub_client.py RPC — lossy hub link",
    "hub.wal_append": "runtime/hub_store.py WAL append — disk write fails",
    "hub.fsync": "runtime/hub_store.py fsync — slow/failing durable disk",
    "engine.step": "engine/core.py step thread — device step fails/stalls",
    "engine.admit": "engine/core.py admission — worker vanishes pre-admit",
    "disagg.pull": "disagg/transfer.py KV pull — transfer plane failure",
}

# metric name (without the dynamo_ prefix MetricsRegistry adds) -> meaning
METRIC_NAMES: dict[str, str] = {
    "http_requests_total": "HTTP requests by model/route/status",
    "time_to_first_token_seconds": "TTFT histogram by model",
    "inter_token_latency_seconds": "ITL histogram by model",
    "request_duration_seconds": "end-to-end request duration by model",
    "output_tokens_total": "generated tokens by model",
    "input_tokens_total": "prompt tokens by model",
    "requests_completed_total": "requests that reached the backend",
    "inflight_requests": "in-flight request gauge by model",
}
