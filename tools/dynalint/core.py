"""dynalint core: findings, suppressions, file walking, the scan driver.

Pure stdlib + pure AST: dynalint never imports the code under analysis, so
it runs in <5s on CPU with no JAX initialisation and cannot be broken by an
import-time crash in the package it is checking.
"""

from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

SUPPRESS_RE = re.compile(
    r"#\s*dynalint:\s*(disable|disable-file)\s*=\s*"
    r"(?P<rules>[A-Z0-9,\s]+?)\s*(?:--\s*(?P<reason>.*))?$"
)
ALL = "ALL"

# Calls that put bytes on (or take bytes off) a wire. The seed set for the
# project-wide wire-taint closure (ProjectIndex): any project function that
# transitively reaches one of these is "wire-tagged" — DL009 refuses to let
# an async lock span await it, and DL007 anchors frame extraction on the
# write_frame sites.
WIRE_PRIMITIVES = frozenset({
    "write_frame", "read_frame", "open_connection", "open_unix_connection",
    "create_connection", "drain",
})

# JAX tracing wrappers the jit registry indexes. ``shard_map`` includes the
# repo's 0.4.x compat shim (ops/shard.py), imported as ``compat_shard_map``
# at every call site.
JIT_WRAPPERS = frozenset({"jit", "pjit"})
SHARD_MAP_WRAPPERS = frozenset({"shard_map", "compat_shard_map"})


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    hint: str = ""
    context: str = ""  # enclosing def/class qualname ("Engine.generate")
    detail: str = ""  # stable token for the fingerprint (not line-based)

    @property
    def fingerprint(self) -> str:
        """Line-number-independent identity: survives unrelated edits to
        the same file, so the committed baseline doesn't churn."""
        raw = f"{self.rule}|{self.path}|{self.context}|{self.detail}"
        return hashlib.sha1(raw.encode()).hexdigest()[:12]

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            out += f"  [fix: {self.hint}]"
        return out


@dataclass
class _Suppression:
    """One ``disable=`` directive and the source lines it covers."""

    rules: frozenset[str]
    lines: frozenset[int]
    declared_line: int
    used: set[str] = field(default_factory=set)  # rules that matched


@dataclass
class Suppressions:
    """Per-file suppression map parsed from the ``disable=`` directives
    (SUPPRESS_RE above; spelled indirectly here so this docstring isn't
    itself parsed as one)."""

    entries: list[_Suppression] = field(default_factory=list)
    file_wide: dict[str, int] = field(default_factory=dict)  # rule -> line
    _file_wide_used: set[str] = field(default_factory=set)

    def covers(self, finding: Finding) -> bool:
        if finding.rule in self.file_wide:
            self._file_wide_used.add(finding.rule)
            return True
        if ALL in self.file_wide:
            self._file_wide_used.add(ALL)
            return True
        hit = False
        for e in self.entries:
            if finding.line in e.lines and (
                finding.rule in e.rules or ALL in e.rules
            ):
                e.used.add(finding.rule)
                hit = True
        return hit

    def unused(self) -> list[tuple[int, str]]:
        """(line, rule) pairs that silenced nothing — a stale disable
        (per-line OR file-wide) would otherwise mask the NEXT real
        finding forever."""
        out = [
            (e.declared_line, r)
            for e in self.entries
            for r in sorted(e.rules)
            if r != ALL and r not in e.used
        ]
        out.extend(
            (line, rule)
            for rule, line in sorted(self.file_wide.items())
            if rule != ALL and rule not in self._file_wide_used
        )
        return sorted(out)


def parse_suppressions(source: str) -> Suppressions:
    sup = Suppressions()
    lines = source.splitlines()
    for i, raw in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(raw)
        if m is None:
            continue
        rules = frozenset(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        if m.group(1) == "disable-file":
            for r in rules:
                sup.file_wide.setdefault(r, i)
            continue
        covered = {i}
        if raw.strip().startswith("#"):
            # comment-only line: the suppression names the next *code*
            # line (reason text may continue over further comment lines)
            j = i + 1
            while j <= len(lines) and (
                not lines[j - 1].strip()
                or lines[j - 1].strip().startswith("#")
            ):
                j += 1
            covered.add(j)
        sup.entries.append(_Suppression(
            rules=rules, lines=frozenset(covered), declared_line=i,
        ))
    return sup


def annotate_parents(tree: ast.AST) -> list[ast.AST]:
    """Attach ``_dl_parent`` to every node (rules walk ancestry for
    try/finally placement, with-blocks, and enclosing scopes) and return
    the flat node list — computed once per file so the six rules don't
    each re-walk the tree (the <5s tier-1 budget is real)."""
    nodes: list[ast.AST] = [tree]
    i = 0
    while i < len(nodes):
        node = nodes[i]
        i += 1
        for child in ast.iter_child_nodes(node):
            child._dl_parent = node  # type: ignore[attr-defined]
            nodes.append(child)
    return nodes


def parents(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_dl_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_dl_parent", None)


def enclosing_function(node: ast.AST):
    """Nearest enclosing function scope (lambda counts: code inside a
    lambda passed to ``asyncio.to_thread`` is NOT on the event loop)."""
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return p
    return None


def qualname(node: ast.AST) -> str:
    """Dotted path of enclosing class/function defs ("Engine.generate")."""
    names: list[str] = []
    cur: ast.AST | None = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.append(cur.name)
        cur = getattr(cur, "_dl_parent", None)
    return ".".join(reversed(names)) or "<module>"


def dotted(node: ast.AST) -> str | None:
    """Resolve an attribute/name chain to a dotted string, or None when a
    segment is dynamic. ``a.b().c`` resolves through calls as ``a.b.c`` so
    ``asyncio.get_running_loop().create_task`` is matchable."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return None


class FunctionInfo:
    """One function/method definition in the project symbol table."""

    __slots__ = (
        "path", "qualname", "node", "is_async", "params", "cls",
        "calls", "has_request_context", "return_call_names",
    )

    def __init__(self, path: str, qual: str, node, cls: str | None):
        self.path = path
        self.qualname = qual
        self.node = node
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        args = node.args
        self.params = tuple(
            a.arg for a in (*args.posonlyargs, *args.args, *args.kwonlyargs)
        )
        self.cls = cls
        # calls made DIRECTLY by this function (nested defs excluded: their
        # bodies only run when the nested function itself is called)
        self.calls: list[tuple[str, ast.Call]] = []
        # dotted names of calls appearing inside a ``return`` expression —
        # the seed observations for the device-returning closure (DL010)
        self.return_call_names: set[str] = set()
        self.has_request_context = any(
            _is_request_context_param(a)
            for a in (*args.posonlyargs, *args.args, *args.kwonlyargs)
        )

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


def _is_request_context_param(arg: ast.arg) -> bool:
    """A parameter that carries the per-request Context (and therefore the
    request deadline). Matched by the repo convention: named ``context``,
    or annotated ``Context`` (``ctx: Context``) — a bare ``ctx`` without
    annotation is NOT assumed (dynalint's own ScanContext convention)."""
    if arg.arg == "context":
        return True
    ann = arg.annotation
    if ann is None:
        return False
    name = dotted(ann) or (
        ann.value if isinstance(ann, ast.Constant)
        and isinstance(ann.value, str) else ""
    )
    return (name or "").rsplit(".", 1)[-1] == "Context"


def _const_int_tuple(node: ast.AST | None) -> tuple[int, ...] | None:
    """``donate_argnums=(5, 6)`` / ``static_argnums=0`` -> (5, 6) / (0,).
    None when absent or not a literal (dynamic specs can't be indexed)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[int] = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def _const_str_tuple(node: ast.AST | None) -> tuple[str, ...] | None:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[str] = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class JitInfo:
    """One ``jax.jit``/``pjit``-wrapped callable in the jit registry.

    Two shapes, both indexed: module-level assignment
    (``decode_steps = jax.jit(decode_steps_impl, donate_argnums=(5, 6))``)
    and decorator (``@jax.jit`` / ``@(functools.)partial(jax.jit, ...)``).
    ``donate_argnums``/``static_argnums``/``static_argnames`` are the
    literal values when literal, else None (unknown)."""

    __slots__ = (
        "path", "name", "context", "line", "col", "kind", "wrapped",
        "donate_argnums", "static_argnums", "static_argnames",
        "wrapped_fn",
    )

    def __init__(self, path: str, name: str, context: str, line: int,
                 col: int, kind: str, wrapped: str | None,
                 donate_argnums, static_argnums, static_argnames):
        self.path = path
        self.name = name  # the callable's public (call-site) name
        self.context = context  # enclosing qualname of the definition
        self.line = line
        self.col = col
        self.kind = kind  # "assign" | "decorator"
        self.wrapped = wrapped  # dotted name of the wrapped impl (assign)
        self.donate_argnums = donate_argnums
        self.static_argnums = static_argnums
        self.static_argnames = static_argnames
        # resolved at finalize(): the wrapped FunctionInfo when findable
        self.wrapped_fn: FunctionInfo | None = None


class ShardMapSite:
    """One ``shard_map``/``compat_shard_map`` call site (incl. the repo's
    ops/shard.py compat shim) with its declared specs, for DL013."""

    __slots__ = (
        "path", "context", "line", "col", "node",
        "in_specs", "out_specs", "wrapped",
    )

    def __init__(self, path: str, context: str, node: ast.Call):
        self.path = path
        self.context = context
        self.node = node
        self.line = node.lineno
        self.col = node.col_offset
        self.in_specs = _kw(node, "in_specs")
        self.out_specs = _kw(node, "out_specs")
        self.wrapped = node.args[0] if node.args else _kw(node, "f")


def _extract_jit_assign(node: ast.Assign, path: str) -> JitInfo | None:
    """``name = jax.jit(impl, static_argnums=..., donate_argnums=...)``."""
    if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
        return None
    call = node.value
    if not isinstance(call, ast.Call):
        return None
    last = (dotted(call.func) or "").rsplit(".", 1)[-1]
    if last not in JIT_WRAPPERS:
        return None
    wrapped = dotted(call.args[0]) if call.args else None
    return JitInfo(
        path=path, name=node.targets[0].id, context=qualname(node),
        line=node.lineno, col=node.col_offset, kind="assign",
        wrapped=wrapped,
        donate_argnums=_const_int_tuple(_kw(call, "donate_argnums")),
        static_argnums=_const_int_tuple(_kw(call, "static_argnums")),
        static_argnames=_const_str_tuple(_kw(call, "static_argnames")),
    )


def _extract_jit_decorator(node, path: str) -> JitInfo | None:
    """``@jax.jit`` / ``@partial(jax.jit, static_argnames=(...))`` on a
    def: the decorated function IS the jitted callable."""
    for dec in node.decorator_list:
        last = (dotted(dec) or "").rsplit(".", 1)[-1]
        kw_src: ast.Call | None = None
        if isinstance(dec, ast.Call):
            if last in JIT_WRAPPERS:
                kw_src = dec  # @jax.jit(static_argnums=...)
            elif last == "partial" and dec.args:
                inner = (dotted(dec.args[0]) or "").rsplit(".", 1)[-1]
                if inner in JIT_WRAPPERS:
                    kw_src = dec  # @partial(jax.jit, ...): kwargs on partial
                else:
                    continue
            else:
                continue
        elif last not in JIT_WRAPPERS:
            continue
        return JitInfo(
            path=path, name=node.name, context=qualname(node),
            # anchor at the DECORATOR: that is where donation/static
            # declarations live, and where a suppression comment lands
            line=dec.lineno, col=dec.col_offset, kind="decorator",
            wrapped=node.name,
            donate_argnums=_const_int_tuple(
                _kw(kw_src, "donate_argnums") if kw_src else None),
            static_argnums=_const_int_tuple(
                _kw(kw_src, "static_argnums") if kw_src else None),
            static_argnames=_const_str_tuple(
                _kw(kw_src, "static_argnames") if kw_src else None),
        )
    return None


class ProjectIndex:
    """Project-wide symbol table + call graph, built once per scan.

    The interprocedural substrate under DL007/DL008/DL009: which functions
    exist, what each one calls, which ones transitively reach a wire
    primitive, and which ones accept a per-request Context. Pure AST —
    method resolution is name-based with a precision bias (self-calls
    resolve within the class; free calls resolve only when every project
    definition of that name agrees)."""

    def __init__(self) -> None:
        self.functions: dict[tuple[str, str], FunctionInfo] = {}
        self.by_name: dict[str, list[FunctionInfo]] = {}
        self.contexts: list["ScanContext"] = []
        self._wire_tainted: set[tuple[str, str]] = set()
        self.context_callee_names: set[str] = set()
        # -- the jit registry (DL010-DL015 substrate) ----------------------
        self.jits: dict[tuple[str, str], JitInfo] = {}  # (path, name)
        self.jit_names: dict[str, list[JitInfo]] = {}
        self.shard_maps: list[ShardMapSite] = []
        # hot closure: functions transitively reachable from a step-thread
        # root (threading.Thread targets + catalog.HOT_PATH_ROOTS)
        self.hot: set[tuple[str, str]] = set()
        self._thread_root_specs: list[tuple] = []
        self._device_returning: set[tuple[str, str]] = set()

    def add_file(self, ctx: "ScanContext") -> None:
        self.contexts.append(ctx)
        # one pass over the pre-built flat node list (NOT a walk per
        # function — the <5s tier-1 budget is real): defs register, calls
        # attach to their nearest enclosing def
        by_node: dict[ast.AST, FunctionInfo] = {}
        for node in ctx.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = qualname(node)
                cls = None
                for p in parents(node):
                    if isinstance(p, ast.ClassDef):
                        cls = p.name
                        break
                    if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        break
                info = FunctionInfo(ctx.path, qual, node, cls)
                by_node[node] = info
                self.functions[(ctx.path, qual)] = info
                if isinstance(node, ast.FunctionDef):
                    jit = _extract_jit_decorator(node, ctx.path)
                    if jit is not None:
                        self.jits[(ctx.path, jit.name)] = jit
            elif isinstance(node, ast.Assign):
                jit = _extract_jit_assign(node, ctx.path)
                if jit is not None:
                    self.jits[(ctx.path, jit.name)] = jit
            elif isinstance(node, ast.Call):
                fn = enclosing_function(node)
                while isinstance(fn, ast.Lambda):
                    fn = enclosing_function(fn)
                info = by_node.get(fn)
                name = dotted(node.func)
                if info is not None and name:
                    info.calls.append((name, node))
                    for p in parents(node):
                        if p is fn:
                            break
                        if isinstance(p, ast.Return):
                            info.return_call_names.add(name)
                            break
                last = (name or "").rsplit(".", 1)[-1]
                if last in SHARD_MAP_WRAPPERS:
                    self.shard_maps.append(
                        ShardMapSite(ctx.path, qualname(node), node)
                    )
                elif last == "Thread":
                    self._note_thread_target(ctx.path, node)

    def _note_thread_target(self, path: str, node: ast.Call) -> None:
        """``threading.Thread(target=self.X / target=fn)``: X/fn is a hot
        root — a dedicated worker thread's entry point (the engine's step
        thread is ``Thread(target=self._thread_loop)``)."""
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                cls = None
                for p in parents(node):
                    if isinstance(p, ast.ClassDef):
                        cls = p.name
                        break
                if cls:
                    self._thread_root_specs.append((path, f"{cls}.{v.attr}"))
            elif isinstance(v, ast.Name):
                self._thread_root_specs.append((path, v.id))

    def finalize(self) -> None:
        self.by_name.clear()
        for info in self.functions.values():
            self.by_name.setdefault(info.name, []).append(info)
        self.context_callee_names = {
            info.name
            for info in self.functions.values()
            if info.has_request_context and not info.name.startswith("__")
        }
        self._compute_wire_taint()
        self.jit_names.clear()
        for (path, _name), jit in self.jits.items():
            self.jit_names.setdefault(jit.name, []).append(jit)
            if jit.wrapped:
                last = jit.wrapped.rsplit(".", 1)[-1]
                jit.wrapped_fn = self.functions.get((path, last))
                if jit.wrapped_fn is None:
                    cands = self.by_name.get(last, [])
                    if len(cands) == 1:
                        jit.wrapped_fn = cands[0]
        self._compute_hot()
        self._compute_device_returning()

    # -- hot closure (step-thread reachability) -----------------------------

    # a bare name with more candidate definitions than this is too generic
    # to propagate hotness through (put/get/run smear the whole project)
    _HOT_FANOUT_CAP = 6

    # method names every stdlib type answers: ``payload.encode()`` must
    # not make VitEncoder.encode hot just because both spell "encode"
    _HOT_GENERIC_METHODS = frozenset({
        "encode", "decode", "items", "keys", "values", "join", "read",
        "write", "close", "copy", "update", "strip", "split", "append",
        "pop", "clear", "add", "remove", "result", "set",
    })

    def _hot_roots(self) -> set[tuple[str, str]]:
        roots = {
            key for key in self._thread_root_specs if key in self.functions
        }
        catalog = None
        if self.contexts:
            catalog = self.contexts[0].catalog
        for spec in getattr(catalog, "HOT_PATH_ROOTS", {}) or {}:
            # "path/suffix.py::Qual.name" — suffix-matched so the catalog
            # entry survives a directory move
            suffix, _, qual = spec.partition("::")
            for (path, q) in self.functions:
                if q == qual and path.endswith(suffix):
                    roots.add((path, q))
        return roots

    def _compute_hot(self) -> None:
        hot = self.hot
        hot.clear()
        frontier = list(self._hot_roots())
        while frontier:
            key = frontier.pop()
            if key in hot:
                continue
            hot.add(key)
            info = self.functions[key]
            for name, _ in info.calls:
                last = name.rsplit(".", 1)[-1]
                if (
                    "." in name
                    and name != f"self.{last}"
                    and last in self._HOT_GENERIC_METHODS
                ):
                    continue
                cands = self._resolve(info, name)
                if not cands or len(cands) > self._HOT_FANOUT_CAP:
                    continue
                for c in cands:
                    # async callees don't run on the step thread (calling
                    # one from it would be its own bug)
                    if c.is_async:
                        continue
                    # a closure can only be called from inside the scope
                    # that defines it — by-name resolution from anywhere
                    # else is always a false edge
                    if enclosing_function(c.node) is not None and not (
                        c.path == info.path
                        and c.qualname.startswith(info.qualname + ".")
                    ):
                        continue
                    k2 = (c.path, c.qualname)
                    if k2 not in hot:
                        frontier.append(k2)

    def is_hot(self, info: FunctionInfo | None) -> bool:
        """Is this function transitively reachable from a step-thread
        root (Thread target or catalogued hot-loop entry)?"""
        return info is not None and (info.path, info.qualname) in self.hot

    # -- device-returning closure (DL010 taint) -----------------------------

    def _compute_device_returning(self) -> None:
        dr = self._device_returning
        dr.clear()
        for key, info in self.functions.items():
            if any(
                n.rsplit(".", 1)[-1] in self.jit_names
                for n in info.return_call_names
            ):
                dr.add(key)
        changed = True
        while changed:
            changed = False
            for key, info in self.functions.items():
                if key in dr:
                    continue
                for name in info.return_call_names:
                    cands = self._resolve(info, name)
                    # same unanimity rule as the wire taint
                    if cands and all(
                        (c.path, c.qualname) in dr for c in cands
                    ):
                        dr.add(key)
                        changed = True
                        break

    def is_device_call(
        self, caller: FunctionInfo | None, name: str
    ) -> bool:
        """Does calling ``name`` from ``caller`` return device values (a
        jit-registry callable, or a function that transitively returns
        one — e.g. the model-family adapter methods)?"""
        if name.rsplit(".", 1)[-1] in self.jit_names:
            return True
        if caller is None:
            return False
        cands = self._resolve(caller, name)
        return bool(cands) and all(
            (c.path, c.qualname) in self._device_returning for c in cands
        )

    # -- wire taint ---------------------------------------------------------

    def _resolve(self, caller: FunctionInfo, name: str) -> list[FunctionInfo]:
        """Best-effort callee resolution for ``name`` as called from
        ``caller``. Exactly ``self.X`` resolves within the caller's class
        (``self.other.X`` is some OTHER object's method — falling through
        to the bare-name candidates); otherwise all project definitions
        of the bare name are returned."""
        last = name.rsplit(".", 1)[-1]
        if name == f"self.{last}" and caller.cls:
            hit = self.functions.get((caller.path, f"{caller.cls}.{last}"))
            if hit is not None:
                return [hit]
        return self.by_name.get(last, [])

    def context_accepting(
        self, caller: FunctionInfo, name: str
    ) -> bool:
        """Does calling ``name`` from ``caller`` reach a context-accepting
        callee? Same unanimity rule as the wire taint: a bare name only
        counts when EVERY project definition of it takes a request
        context — ``cache.put`` must not smear just because some other
        ``put`` somewhere accepts one."""
        cands = self._resolve(caller, name)
        return bool(cands) and all(c.has_request_context for c in cands)

    def _compute_wire_taint(self) -> None:
        tainted = self._wire_tainted
        tainted.clear()
        for key, info in self.functions.items():
            if any(
                n.rsplit(".", 1)[-1] in WIRE_PRIMITIVES
                for n, _ in info.calls
            ):
                tainted.add(key)
        changed = True
        while changed:
            changed = False
            for key, info in self.functions.items():
                if key in tainted:
                    continue
                for name, _ in info.calls:
                    cands = self._resolve(info, name)
                    # unanimity rule (precision over recall): only taint
                    # through a bare name when EVERY definition of it is
                    # tainted — InMemoryHub.put must not smear RemoteHub
                    # taint onto queue.put
                    if cands and all(
                        (c.path, c.qualname) in tainted for c in cands
                    ):
                        tainted.add(key)
                        changed = True
                        break

    def is_wire_call(
        self, caller: FunctionInfo | None, name: str
    ) -> bool:
        """Does calling ``name`` (dotted) from ``caller`` reach the wire?"""
        if name.rsplit(".", 1)[-1] in WIRE_PRIMITIVES:
            return True
        if caller is None:
            return False
        cands = self._resolve(caller, name)
        return bool(cands) and all(
            (c.path, c.qualname) in self._wire_tainted for c in cands
        )

    def function_at(self, path: str, node: ast.AST) -> FunctionInfo | None:
        fn = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) else enclosing_function(node)
        while isinstance(fn, ast.Lambda):
            fn = enclosing_function(fn)
        if fn is None:
            return None
        return self.functions.get((path, qualname(fn)))


class ScanContext:
    """Everything one rule invocation gets to look at for one file."""

    def __init__(
        self,
        tree: ast.Module,
        source: str,
        path: str,
        catalog=None,
        nodes: list[ast.AST] | None = None,
    ):
        self.tree = tree
        self.source = source
        self.path = path
        self.lines = source.splitlines()
        # flat pre-order node list; every rule iterates this instead of
        # re-walking the tree
        self.nodes = annotate_parents(tree) if nodes is None else nodes
        # modules that participate in the async/threaded runtime: a sync
        # time.sleep in one of these is loop-reachable until proven
        # otherwise (DL001 tier 2)
        self.imports_async_runtime = any(
            isinstance(n, (ast.Import, ast.ImportFrom))
            and any(
                (a.name if isinstance(n, ast.Import) else n.module or "")
                .split(".")[0] in ("asyncio", "threading")
                for a in n.names
            )
            for n in self.nodes
        )
        if catalog is None:
            from tools.dynalint import catalog as catalog_mod

            catalog = catalog_mod
        self.catalog = catalog
        # cross-file accumulators (runner-owned; rules append)
        self.used_fault_sites: set[str] = set()
        self.used_metric_names: set[str] = set()
        self.used_span_names: set[str] = set()
        # per-file notices the runner surfaces (unused suppressions)
        self.warnings: list[str] = []
        # the project-wide symbol table / call graph; set by the runner
        # before any rule runs (single-file scans get a one-file index)
        self.project: ProjectIndex | None = None


def _parse_file(
    path: Path, root: Path, catalog=None
) -> tuple[ScanContext | None, Suppressions | None, Finding | None]:
    """Parse one file into a ScanContext (+its suppressions), or a DL000
    syntax-error finding."""
    rel = path.resolve().relative_to(root.resolve()).as_posix()
    source = path.read_text(encoding="utf-8", errors="replace")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        f = Finding(
            rule="DL000",
            path=rel,
            line=e.lineno or 1,
            col=e.offset or 0,
            message=f"syntax error: {e.msg}",
            detail="syntax-error",
        )
        return None, None, f
    ctx = ScanContext(tree, source, rel, catalog=catalog)
    return ctx, parse_suppressions(source), None


def _run_rules(
    ctxs: list[tuple[ScanContext, Suppressions]],
    project: ProjectIndex,
    rules=None,
) -> tuple[list[Finding], list[Finding]]:
    """Run per-file rules over every ctx, then the project-level rules
    over the whole index; route each finding through its own file's
    suppressions."""
    from tools.dynalint.rules import PROJECT_RULES, RULES

    sups = {ctx.path: sup for ctx, sup in ctxs}
    active: list[Finding] = []
    suppressed: list[Finding] = []

    def route(finding: Finding) -> None:
        sup = sups.get(finding.path)
        if sup is not None and sup.covers(finding):
            suppressed.append(finding)
        else:
            active.append(finding)

    for ctx, _sup in ctxs:
        ctx.project = project
        for rule_id, rule in RULES.items():
            if rules is not None and rule_id not in rules:
                continue
            if rule_id in PROJECT_RULES:
                continue  # runs once over the index, below
            for finding in rule.check(ctx):
                route(finding)
    for rule_id in PROJECT_RULES:
        if rules is not None and rule_id not in rules:
            continue
        rule = RULES[rule_id]
        for finding in rule.check_project(project):
            route(finding)
    if rules is None:
        # only meaningful under the full rule set: a DL004 disable looks
        # "unused" when DL004 wasn't run
        for ctx, sup in ctxs:
            for line, rule_id in sup.unused():
                ctx.warnings.append(
                    f"{ctx.path}:{line}: unused suppression for {rule_id} "
                    "— the finding is gone; remove the disable before it "
                    "masks a new one"
                )
    return active, suppressed


def scan_file(
    path: Path,
    root: Path,
    rules=None,
    catalog=None,
) -> tuple[list[Finding], list[Finding], ScanContext | None]:
    """Scan one file standalone (fixtures, ad-hoc checks). Project-level
    rules run over a one-file index, so a self-contained fixture can pin
    DL007 behavior. Returns (active, suppressed, ctx); ctx is None when
    the file failed to parse (which is itself a finding)."""
    ctx, sup, err = _parse_file(path, root, catalog=catalog)
    if err is not None:
        return [err], [], None
    project = ProjectIndex()
    project.add_file(ctx)
    project.finalize()
    active, suppressed = _run_rules([(ctx, sup)], project, rules=rules)
    return active, suppressed, ctx


def iter_python_files(paths: list[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                if "dynalint" in f.parts and "fixtures" in f.parts:
                    # the golden fixtures are findings BY DESIGN; scanning
                    # tools/ must not turn them into gate failures
                    continue
                yield f


def build_index(paths: list[Path], root: Path, catalog=None) -> ProjectIndex:
    """Parse ``paths`` into a finalized ProjectIndex without running any
    rules (wire-schema extraction / --emit-protocol)."""
    project = ProjectIndex()
    for path in iter_python_files(paths):
        ctx, _sup, err = _parse_file(path, root, catalog=catalog)
        if err is None:
            project.add_file(ctx)
    project.finalize()
    return project


def run_paths(
    paths: list[Path],
    root: Path,
    rules=None,
    catalog=None,
    wire_schema_path: Path | None = None,
) -> tuple[list[Finding], list[Finding], list[str]]:
    """Scan all files under ``paths``. Returns (findings, suppressed,
    cross-file warnings). Warnings cover catalog drift in the *stale*
    direction — a catalogued fault site, metric name, or wire op that no
    code uses — which can't be attributed to any single file.

    ``wire_schema_path``: when set (the CLI passes it for default-scope
    scans), the extracted wire schema is additionally diffed against this
    committed catalog in both directions (DL007)."""
    ctxs: list[tuple[ScanContext, Suppressions]] = []
    findings: list[Finding] = []
    project = ProjectIndex()
    for path in iter_python_files(paths):
        ctx, sup, err = _parse_file(path, root, catalog=catalog)
        if err is not None:
            findings.append(err)
            continue
        ctxs.append((ctx, sup))
        project.add_file(ctx)
    project.finalize()
    active, suppressed = _run_rules(ctxs, project, rules=rules)
    findings.extend(active)
    warnings: list[str] = []
    for ctx, _sup in ctxs:
        warnings.extend(ctx.warnings)
    if catalog is None:
        from tools.dynalint import catalog as catalog_mod

        catalog = catalog_mod
    # stale-catalog detection only makes sense over a whole tree: a
    # single-file scan trivially "doesn't use" almost every entry
    if any(p.is_dir() for p in paths):
        used_sites: set[str] = set()
        used_metrics: set[str] = set()
        used_spans: set[str] = set()
        for ctx, _sup in ctxs:
            used_sites |= ctx.used_fault_sites
            used_metrics |= ctx.used_metric_names
            used_spans |= ctx.used_span_names
        if rules is None or "DL006" in rules:
            for site in sorted(set(catalog.FAULT_SITES) - used_sites):
                warnings.append(
                    f"catalog: fault site {site!r} is documented but no "
                    f"faults fire()/fire_sync()/corrupt_bytes() call uses "
                    f"it (stale catalog entry?)"
                )
            for name in sorted(set(catalog.METRIC_NAMES) - used_metrics):
                warnings.append(
                    f"catalog: metric {name!r} is documented but never "
                    f"registered (stale catalog entry?)"
                )
            span_catalog = set(getattr(catalog, "SPAN_NAMES", ()))
            for name in sorted(span_catalog - used_spans):
                warnings.append(
                    f"catalog: span {name!r} is documented but never "
                    f"emitted (stale catalog entry?)"
                )
        if rules is None or "DL007" in rules:
            from tools.dynalint import wire

            warnings.extend(wire.unsent_op_warnings(project))
            if wire_schema_path is not None:
                findings.extend(
                    wire.schema_drift_findings(project, wire_schema_path)
                )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed, warnings
