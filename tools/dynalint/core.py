"""dynalint core: findings, suppressions, file walking, the scan driver.

Pure stdlib + pure AST: dynalint never imports the code under analysis, so
it runs in <5s on CPU with no JAX initialisation and cannot be broken by an
import-time crash in the package it is checking.
"""

from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

SUPPRESS_RE = re.compile(
    r"#\s*dynalint:\s*(disable|disable-file)\s*=\s*"
    r"(?P<rules>[A-Z0-9,\s]+?)\s*(?:--\s*(?P<reason>.*))?$"
)
ALL = "ALL"


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    hint: str = ""
    context: str = ""  # enclosing def/class qualname ("Engine.generate")
    detail: str = ""  # stable token for the fingerprint (not line-based)

    @property
    def fingerprint(self) -> str:
        """Line-number-independent identity: survives unrelated edits to
        the same file, so the committed baseline doesn't churn."""
        raw = f"{self.rule}|{self.path}|{self.context}|{self.detail}"
        return hashlib.sha1(raw.encode()).hexdigest()[:12]

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            out += f"  [fix: {self.hint}]"
        return out


@dataclass
class _Suppression:
    """One ``disable=`` directive and the source lines it covers."""

    rules: frozenset[str]
    lines: frozenset[int]
    declared_line: int
    used: set[str] = field(default_factory=set)  # rules that matched


@dataclass
class Suppressions:
    """Per-file suppression map parsed from ``# dynalint: disable=...``."""

    entries: list[_Suppression] = field(default_factory=list)
    file_wide: dict[str, int] = field(default_factory=dict)  # rule -> line
    _file_wide_used: set[str] = field(default_factory=set)

    def covers(self, finding: Finding) -> bool:
        if finding.rule in self.file_wide:
            self._file_wide_used.add(finding.rule)
            return True
        if ALL in self.file_wide:
            self._file_wide_used.add(ALL)
            return True
        hit = False
        for e in self.entries:
            if finding.line in e.lines and (
                finding.rule in e.rules or ALL in e.rules
            ):
                e.used.add(finding.rule)
                hit = True
        return hit

    def unused(self) -> list[tuple[int, str]]:
        """(line, rule) pairs that silenced nothing — a stale disable
        (per-line OR file-wide) would otherwise mask the NEXT real
        finding forever."""
        out = [
            (e.declared_line, r)
            for e in self.entries
            for r in sorted(e.rules)
            if r != ALL and r not in e.used
        ]
        out.extend(
            (line, rule)
            for rule, line in sorted(self.file_wide.items())
            if rule != ALL and rule not in self._file_wide_used
        )
        return sorted(out)


def parse_suppressions(source: str) -> Suppressions:
    sup = Suppressions()
    lines = source.splitlines()
    for i, raw in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(raw)
        if m is None:
            continue
        rules = frozenset(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        if m.group(1) == "disable-file":
            for r in rules:
                sup.file_wide.setdefault(r, i)
            continue
        covered = {i}
        if raw.strip().startswith("#"):
            # comment-only line: the suppression names the next *code*
            # line (reason text may continue over further comment lines)
            j = i + 1
            while j <= len(lines) and (
                not lines[j - 1].strip()
                or lines[j - 1].strip().startswith("#")
            ):
                j += 1
            covered.add(j)
        sup.entries.append(_Suppression(
            rules=rules, lines=frozenset(covered), declared_line=i,
        ))
    return sup


def annotate_parents(tree: ast.AST) -> list[ast.AST]:
    """Attach ``_dl_parent`` to every node (rules walk ancestry for
    try/finally placement, with-blocks, and enclosing scopes) and return
    the flat node list — computed once per file so the six rules don't
    each re-walk the tree (the <5s tier-1 budget is real)."""
    nodes: list[ast.AST] = [tree]
    i = 0
    while i < len(nodes):
        node = nodes[i]
        i += 1
        for child in ast.iter_child_nodes(node):
            child._dl_parent = node  # type: ignore[attr-defined]
            nodes.append(child)
    return nodes


def parents(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_dl_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_dl_parent", None)


def enclosing_function(node: ast.AST):
    """Nearest enclosing function scope (lambda counts: code inside a
    lambda passed to ``asyncio.to_thread`` is NOT on the event loop)."""
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return p
    return None


def qualname(node: ast.AST) -> str:
    """Dotted path of enclosing class/function defs ("Engine.generate")."""
    names: list[str] = []
    cur: ast.AST | None = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.append(cur.name)
        cur = getattr(cur, "_dl_parent", None)
    return ".".join(reversed(names)) or "<module>"


def dotted(node: ast.AST) -> str | None:
    """Resolve an attribute/name chain to a dotted string, or None when a
    segment is dynamic. ``a.b().c`` resolves through calls as ``a.b.c`` so
    ``asyncio.get_running_loop().create_task`` is matchable."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return None


class ScanContext:
    """Everything one rule invocation gets to look at for one file."""

    def __init__(
        self,
        tree: ast.Module,
        source: str,
        path: str,
        catalog=None,
        nodes: list[ast.AST] | None = None,
    ):
        self.tree = tree
        self.source = source
        self.path = path
        self.lines = source.splitlines()
        # flat pre-order node list; every rule iterates this instead of
        # re-walking the tree
        self.nodes = annotate_parents(tree) if nodes is None else nodes
        # modules that participate in the async/threaded runtime: a sync
        # time.sleep in one of these is loop-reachable until proven
        # otherwise (DL001 tier 2)
        self.imports_async_runtime = any(
            isinstance(n, (ast.Import, ast.ImportFrom))
            and any(
                (a.name if isinstance(n, ast.Import) else n.module or "")
                .split(".")[0] in ("asyncio", "threading")
                for a in n.names
            )
            for n in self.nodes
        )
        if catalog is None:
            from tools.dynalint import catalog as catalog_mod

            catalog = catalog_mod
        self.catalog = catalog
        # cross-file accumulators (runner-owned; rules append)
        self.used_fault_sites: set[str] = set()
        self.used_metric_names: set[str] = set()
        # per-file notices the runner surfaces (unused suppressions)
        self.warnings: list[str] = []


def scan_file(
    path: Path,
    root: Path,
    rules=None,
    catalog=None,
) -> tuple[list[Finding], list[Finding], ScanContext | None]:
    """Scan one file. Returns (active findings, suppressed findings, ctx);
    ctx is None when the file failed to parse (which is itself a finding)."""
    from tools.dynalint.rules import RULES

    rel = path.resolve().relative_to(root.resolve()).as_posix()
    source = path.read_text(encoding="utf-8", errors="replace")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        f = Finding(
            rule="DL000",
            path=rel,
            line=e.lineno or 1,
            col=e.offset or 0,
            message=f"syntax error: {e.msg}",
            detail="syntax-error",
        )
        return [f], [], None
    ctx = ScanContext(tree, source, rel, catalog=catalog)
    sup = parse_suppressions(source)
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for rule_id, rule in RULES.items():
        if rules is not None and rule_id not in rules:
            continue
        for finding in rule.check(ctx):
            (suppressed if sup.covers(finding) else active).append(finding)
    if rules is None:
        # only meaningful under the full rule set: a DL004 disable looks
        # "unused" when DL004 wasn't run
        for line, rule_id in sup.unused():
            ctx.warnings.append(
                f"{rel}:{line}: unused suppression for {rule_id} — the "
                "finding is gone; remove the disable before it masks a "
                "new one"
            )
    return active, suppressed, ctx


def iter_python_files(paths: list[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                yield f


def run_paths(
    paths: list[Path],
    root: Path,
    rules=None,
    catalog=None,
) -> tuple[list[Finding], list[Finding], list[str]]:
    """Scan all files under ``paths``. Returns (findings, suppressed,
    cross-file warnings). Warnings cover catalog drift in the *stale*
    direction — a catalogued fault site or metric name that no code uses —
    which can't be attributed to any single file."""
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    used_sites: set[str] = set()
    used_metrics: set[str] = set()
    warnings: list[str] = []
    for path in iter_python_files(paths):
        active, supp, ctx = scan_file(path, root, rules=rules, catalog=catalog)
        findings.extend(active)
        suppressed.extend(supp)
        if ctx is not None:
            used_sites |= ctx.used_fault_sites
            used_metrics |= ctx.used_metric_names
            warnings.extend(ctx.warnings)
    if catalog is None:
        from tools.dynalint import catalog as catalog_mod

        catalog = catalog_mod
    # stale-catalog detection only makes sense over a whole tree: a
    # single-file scan trivially "doesn't use" almost every entry
    if any(p.is_dir() for p in paths) and (rules is None or "DL006" in rules):
        for site in sorted(set(catalog.FAULT_SITES) - used_sites):
            warnings.append(
                f"catalog: fault site {site!r} is documented but no "
                f"faults.fire()/fire_sync() call uses it (stale catalog entry?)"
            )
        for name in sorted(set(catalog.METRIC_NAMES) - used_metrics):
            warnings.append(
                f"catalog: metric {name!r} is documented but never "
                f"registered (stale catalog entry?)"
            )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed, warnings
