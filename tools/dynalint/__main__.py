import sys

from tools.dynalint.cli import main

sys.exit(main())
