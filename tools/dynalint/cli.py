"""dynalint CLI: the single static-analysis entry point for this repo.

``python -m tools.dynalint`` runs, in order:

  1. the dynalint rule suite (DL001–DL006) against the committed baseline;
  2. ``ruff check`` with the pyproject config, when ruff is installed;
  3. ``mypy`` (strict on dynamo_tpu/runtime/), when mypy is installed.

Missing external tools are *skipped with a notice*, never a failure — the
hermetic CI container bakes only the Python toolchain, and the dynalint
rules themselves are pure stdlib. Exit code 0 = the combined pass is green.
"""

from __future__ import annotations

import argparse
import json
import shutil
import subprocess
import sys
import time
from pathlib import Path

from tools.dynalint import baseline as baseline_mod
from tools.dynalint.core import run_paths
from tools.dynalint.rules import RULES

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def _run_external(name: str, argv: list[str]) -> int | None:
    """Run an optional external checker; None = not installed (skipped).
    Notices go to stderr: stdout belongs to findings (and, under --json,
    to the one JSON document)."""
    if shutil.which(name) is None and shutil.which(argv[0]) is None:
        print(f"dynalint: {name} not installed — skipped "
              f"(pip install .[dev] to enable)", file=sys.stderr)
        return None
    proc = subprocess.run(argv, cwd=REPO_ROOT)
    return proc.returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynalint",
        description="Project-specific static analysis for dynamo-tpu.",
    )
    ap.add_argument("paths", nargs="*", default=["dynamo_tpu"],
                    help="files/dirs to scan (default: dynamo_tpu)")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline JSON (default: committed baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(DL001/DL002 are never baselined)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (e.g. DL001,DL004)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-external", action="store_true",
                    help="skip ruff/mypy even when installed")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list suppressed findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rid}  {rule.name:<26} {doc}")
        return 0

    rules = None
    if args.rules:
        rules = {r.strip().upper() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"dynalint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    t0 = time.monotonic()
    paths = [
        p if p.is_absolute() else REPO_ROOT / p
        for p in (Path(p) for p in args.paths)
    ]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"dynalint: no such path: {missing[0]}", file=sys.stderr)
        return 2
    findings, suppressed, warnings = run_paths(paths, REPO_ROOT, rules=rules)

    base = {} if args.no_baseline else baseline_mod.load(Path(args.baseline))
    new, grandfathered, stale = baseline_mod.split(findings, base)

    if args.update_baseline:
        baseline_mod.save(Path(args.baseline), findings)
        print(f"dynalint: baseline rewritten with "
              f"{len([f for f in findings if f.rule not in baseline_mod.NEVER_BASELINE])} "
              f"finding(s) -> {args.baseline}", file=sys.stderr)
        new = [f for f in findings
               if f.rule in baseline_mod.NEVER_BASELINE]

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "grandfathered": [f.fingerprint for f in grandfathered],
            "stale_baseline": [e["fingerprint"] for e in stale],
            "suppressed": len(suppressed),
            "warnings": warnings,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"[suppressed] {f.render()}")
        for w in warnings:
            print(f"dynalint: warning: {w}", file=sys.stderr)
        for e in stale:
            print(
                f"dynalint: warning: stale baseline entry "
                f"{e['fingerprint']} ({e['rule']} {e['path']} "
                f"{e.get('context', '')}) — fixed? run --update-baseline",
                file=sys.stderr,
            )
        dt = time.monotonic() - t0
        print(
            f"dynalint: {len(new)} new, {len(grandfathered)} baselined, "
            f"{len(suppressed)} suppressed finding(s) in {dt:.2f}s",
            file=sys.stderr,
        )

    rc = 1 if new else 0

    # --json promises exactly one parseable document on stdout; external
    # tools write their own stdout, so they only chain in text mode
    if (
        rc == 0 and not args.no_external and not args.update_baseline
        and not args.as_json
    ):
        ruff_rc = _run_external(
            "ruff", ["ruff", "check", *[str(p) for p in args.paths]]
        )
        if ruff_rc not in (None, 0):
            rc = 1
        mypy_rc = _run_external(
            "mypy", ["mypy", "--config-file", "pyproject.toml",
                     "dynamo_tpu/runtime"]
        )
        if mypy_rc not in (None, 0):
            rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
