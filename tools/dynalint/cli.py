"""dynalint CLI: the single static-analysis entry point for this repo.

``python -m tools.dynalint`` runs, in order:

  1. the dynalint rule suite (DL001–DL009, incl. the interprocedural
     wire-schema/deadline/lock passes) against the committed baseline
     and the committed wire-protocol catalog (wire_schema.json);
  2. ``ruff check`` with the pyproject config, when ruff is installed;
  3. ``mypy`` (strict on dynamo_tpu/runtime/), when mypy is installed.

Missing external tools are *skipped with a notice*, never a failure — the
hermetic CI container bakes only the Python toolchain, and the dynalint
rules themselves are pure stdlib. Exit code 0 = the combined pass is green.

Output modes: default text, ``--format=github`` (GitHub Actions
annotation lines), ``--json`` (one machine-readable document).
``--changed-only`` scans the full default scope (the interprocedural
passes need the whole project) but reports only findings in files your
git working tree touches — the pre-commit sweet spot.
"""

from __future__ import annotations

import argparse
import json
import shutil
import subprocess
import sys
import time
from pathlib import Path

from tools.dynalint import baseline as baseline_mod
from tools.dynalint import wire
from tools.dynalint.core import build_index, run_paths
from tools.dynalint.rules import PROJECT_RULES, RULES

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
# the default scan scope: the package, the tooling (dynalint checks
# itself), and the shared cluster test helper (it spawns replica
# subprocesses and speaks the repl.* wire protocol too)
DEFAULT_PATHS = ["dynamo_tpu", "tools", "tests/hub_cluster.py"]
DEFAULT_PROTOCOL_MD = "docs/PROTOCOL.md"


def changed_files(
    root: Path, scope: tuple[Path, ...] = ()
) -> set[str] | None:
    """Repo-relative paths the git working tree touches (staged,
    unstaged, and untracked), or None when git is unavailable.

    ``scope`` narrows the git query to the configured scan paths: a
    dirty ``deploy/`` file must read as "no SCANNED file changed", not
    as a repo-wide dirty state that withholds every finding."""
    specs: list[str] = []
    for p in scope:
        try:
            specs.append(str(p.relative_to(root)) if p.is_absolute()
                         else str(p))
        except ValueError:  # outside the repo: git can't scope to it
            return None
    try:
        # -uall: a brand-new directory must list its files individually
        # (plain porcelain collapses them to "?? dir/", which would
        # silently withhold every finding inside it)
        proc = subprocess.run(
            ["git", "status", "--porcelain", "-uall",
             *(["--", *specs] if specs else [])],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out: set[str] = set()
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: report the new side
            path = path.split(" -> ", 1)[1]
        if path.startswith('"') and path.endswith('"'):
            # core.quotePath: non-ASCII names arrive C-style quoted with
            # octal escapes; left undecoded they'd never match a
            # Finding.path and the finding would be silently withheld
            try:
                path = (
                    path[1:-1].encode("latin-1")
                    .decode("unicode_escape")
                    .encode("latin-1").decode("utf-8")
                )
            except (UnicodeDecodeError, UnicodeEncodeError):
                path = path.strip('"')
        out.add(path)
    return out


def render_sarif(findings) -> str:
    """SARIF 2.1.0 document for code-scanning upload, via the shared
    tools/_sarif.py emitter (dynarace emits the same shape): one run,
    the full rule catalog in tool.driver.rules, stable
    partialFingerprints (the finding's line-independent fingerprint, so
    annotations track across rebases the same way the baseline does)."""
    from tools import _sarif

    rules = []
    for rid in sorted(RULES):
        rule = RULES[rid]
        doc = (rule.__doc__ or "").strip().splitlines()
        rules.append(_sarif.SarifRule(
            id=rid, name=rule.name,
            short=doc[0] if doc else rule.name,
            full=" ".join(line.strip() for line in doc).strip(),
        ))
    results = [
        _sarif.SarifResult(
            rule_id=f.rule,
            message=f.message + (f"  [fix: {f.hint}]" if f.hint else ""),
            uri=f.path, line=f.line, col=f.col + 1,
            fingerprint=f.fingerprint,
        )
        for f in findings
    ]
    return _sarif.render(
        "dynalint",
        "https://example.invalid/dynamo-tpu/tools/dynalint",
        rules, results, "dynalintFingerprint/v1",
    )


def render_github(f) -> str:
    """GitHub Actions workflow-command annotation line."""
    msg = f.message + (f"  [fix: {f.hint}]" if f.hint else "")
    # workflow-command data must stay one line
    msg = msg.replace("%", "%25").replace("\n", " ")
    return (
        f"::error file={f.path},line={f.line},col={f.col + 1},"
        f"title=dynalint {f.rule}::{msg}"
    )


def _run_external(name: str, argv: list[str]) -> int | None:
    """Run an optional external checker; None = not installed (skipped).
    Notices go to stderr: stdout belongs to findings (and, under --json,
    to the one JSON document)."""
    if shutil.which(name) is None and shutil.which(argv[0]) is None:
        print(f"dynalint: {name} not installed — skipped "
              f"(pip install .[dev] to enable)", file=sys.stderr)
        return None
    proc = subprocess.run(argv, cwd=REPO_ROOT)
    return proc.returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynalint",
        description="Project-specific static analysis for dynamo-tpu.",
    )
    ap.add_argument("paths", nargs="*", default=DEFAULT_PATHS,
                    help=f"files/dirs to scan (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline JSON (default: committed baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(DL001/DL002/DL007 are never baselined)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (e.g. DL001,DL004)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--format", default="text",
                    choices=["text", "github", "sarif"],
                    help="finding output format: text (default), github "
                         "(Actions ::error annotations), or sarif "
                         "(one SARIF 2.1.0 document for code-scanning "
                         "upload)")
    ap.add_argument("--changed-only", action="store_true",
                    help="scan the full scope (interprocedural passes "
                         "need it) but report only findings in files the "
                         "git working tree touches")
    ap.add_argument("--update-wire-schema", action="store_true",
                    help="rewrite tools/dynalint/wire_schema.json from "
                         "the extracted protocol")
    ap.add_argument("--emit-protocol", nargs="?", const=DEFAULT_PROTOCOL_MD,
                    default=None, metavar="PATH",
                    help="render the wire schema to a human-readable "
                         f"markdown catalog (default {DEFAULT_PROTOCOL_MD})")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-external", action="store_true",
                    help="skip ruff/mypy even when installed")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list suppressed findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rid}  {rule.name:<26} {doc}")
        return 0

    rules = None
    if args.rules:
        rules = {r.strip().upper() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"dynalint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    t0 = time.monotonic()
    # resolve before comparing: "dynamo_tpu/" or a reordered spelling of
    # the default scope must not silently disable the (never-
    # baselineable) wire-schema drift check
    full_scope = {
        (REPO_ROOT / p).resolve() for p in args.paths
    } == {(REPO_ROOT / p).resolve() for p in DEFAULT_PATHS}
    paths = [
        p if p.is_absolute() else REPO_ROOT / p
        for p in (Path(p) for p in args.paths)
    ]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"dynalint: no such path: {missing[0]}", file=sys.stderr)
        return 2

    if args.update_wire_schema or args.emit_protocol is not None:
        # catalog maintenance mode: extract over the FULL default scope
        # (a partial extraction would record a partial protocol) and
        # write; the gate run stays separate
        index = build_index(
            [REPO_ROOT / p for p in DEFAULT_PATHS], REPO_ROOT
        )
        canonical = wire.extract(index).to_canonical()
        if args.update_wire_schema:
            wire.save_schema(index, wire.SCHEMA_PATH)
            n_ops = sum(len(v) for v in canonical["channels"].values())
            print(f"dynalint: wire schema rewritten ({n_ops} ops across "
                  f"{len(canonical['channels'])} channels) -> "
                  f"{wire.SCHEMA_PATH}", file=sys.stderr)
        if args.emit_protocol is not None:
            out = Path(args.emit_protocol)
            if not out.is_absolute():
                out = REPO_ROOT / out
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_text(wire.render_protocol_md(canonical))
            print(f"dynalint: protocol catalog rendered -> {out}",
                  file=sys.stderr)
        return 0

    findings, suppressed, warnings = run_paths(
        paths, REPO_ROOT, rules=rules,
        # the committed-catalog drift check needs the full picture: a
        # partial scan would see "missing" ops that are merely out of
        # scope
        wire_schema_path=wire.SCHEMA_PATH if full_scope else None,
    )

    base = {} if args.no_baseline else baseline_mod.load(Path(args.baseline))
    new, grandfathered, stale = baseline_mod.split(findings, base)

    if args.changed_only:
        changed = changed_files(REPO_ROOT, tuple(paths))
        if changed is None:
            print("dynalint: --changed-only needs git; reporting all "
                  "findings", file=sys.stderr)
        else:
            before = len(new)
            # project-level rules (DL007/DL015) attribute findings to
            # the OTHER side of the drift/inversion — the sender file or
            # the committed catalog — which may not be the file that was
            # edited; withholding those would let a protocol break commit
            new = [
                f for f in new
                if f.path in changed or f.rule in PROJECT_RULES
            ]
            if not changed:
                print("dynalint: --changed-only: no file in the scan "
                      "scope is dirty; per-file findings withheld "
                      "(project-level rules still report)",
                      file=sys.stderr)
            elif before != len(new):
                print(f"dynalint: --changed-only: {before - len(new)} "
                      "finding(s) in untouched files withheld",
                      file=sys.stderr)

    if args.update_baseline:
        baseline_mod.save(Path(args.baseline), findings)
        print(f"dynalint: baseline rewritten with "
              f"{len([f for f in findings if f.rule not in baseline_mod.NEVER_BASELINE])} "
              f"finding(s) -> {args.baseline}", file=sys.stderr)
        new = [f for f in findings
               if f.rule in baseline_mod.NEVER_BASELINE]

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "grandfathered": [f.fingerprint for f in grandfathered],
            "stale_baseline": [e["fingerprint"] for e in stale],
            "suppressed": len(suppressed),
            "warnings": warnings,
        }, indent=2))
    elif args.format == "sarif":
        print(render_sarif(new))
        for w in warnings:
            print(f"dynalint: warning: {w}", file=sys.stderr)
    else:
        for f in new:
            print(render_github(f) if args.format == "github"
                  else f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"[suppressed] {f.render()}")
        for w in warnings:
            print(f"dynalint: warning: {w}", file=sys.stderr)
        for e in stale:
            print(
                f"dynalint: warning: stale baseline entry "
                f"{e['fingerprint']} ({e['rule']} {e['path']} "
                f"{e.get('context', '')}) — fixed? run --update-baseline",
                file=sys.stderr,
            )
        dt = time.monotonic() - t0
        print(
            f"dynalint: {len(new)} new, {len(grandfathered)} baselined, "
            f"{len(suppressed)} suppressed finding(s) in {dt:.2f}s",
            file=sys.stderr,
        )

    rc = 1 if new else 0

    # --json/--format=sarif promise exactly one parseable document on
    # stdout; external tools write their own stdout, so they only chain
    # in text mode
    if (
        rc == 0 and not args.no_external and not args.update_baseline
        and not args.as_json and args.format != "sarif"
    ):
        ruff_rc = _run_external(
            "ruff", ["ruff", "check", *[str(p) for p in args.paths]]
        )
        if ruff_rc not in (None, 0):
            rc = 1
        mypy_rc = _run_external(
            "mypy", ["mypy", "--config-file", "pyproject.toml",
                     "dynamo_tpu/runtime"]
        )
        if mypy_rc not in (None, 0):
            rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
