"""The dynalint rule set (DL001–DL009).

Each rule encodes an invariant this repo has already paid for in bugs
(see tools/dynalint/README.md for the incident each rule back-references).
DL001–DL006 are pure-AST ``check(ctx) -> list[Finding]`` callables over
one file (DL006 additionally feeds the runner's cross-file stale-catalog
check). DL007–DL009 ride the project-wide symbol table + call graph
(core.ProjectIndex): DL007 is a project-level rule
(``check_project(index)``), DL008/DL009 are per-file rules that consult
the index for callee resolution and wire-taint.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.dynalint.core import (
    Finding,
    ProjectIndex,
    ScanContext,
    dotted,
    enclosing_function,
    parents,
    qualname,
)

# --------------------------------------------------------------------------
# DL001 blocking-call-in-async
# --------------------------------------------------------------------------

# Calls that park the calling OS thread. Inside ``async def`` they park the
# event loop itself: every in-flight stream on this process stalls behind
# them (the TTFT-tail failure mode PR 3 hand-fixed in the engine).
BLOCKING_CALLS = {
    "time.sleep": "await asyncio.sleep(...)",
    "subprocess.run": "await asyncio.create_subprocess_exec(...)",
    "subprocess.call": "await asyncio.create_subprocess_exec(...)",
    "subprocess.check_call": "await asyncio.create_subprocess_exec(...)",
    "subprocess.check_output": "await asyncio.create_subprocess_exec(...)",
    "os.system": "await asyncio.create_subprocess_shell(...)",
    "urllib.request.urlopen": "await asyncio.to_thread(urllib.request.urlopen, ...)",
    "socket.create_connection": "await asyncio.open_connection(...)",
    "requests.get": "aiohttp / asyncio.to_thread",
    "requests.post": "aiohttp / asyncio.to_thread",
    "requests.put": "aiohttp / asyncio.to_thread",
    "requests.delete": "aiohttp / asyncio.to_thread",
    "requests.head": "aiohttp / asyncio.to_thread",
    "requests.request": "aiohttp / asyncio.to_thread",
}


class BlockingCallInAsync:
    """DL001: blocking call reachable from the event loop.

    Two tiers:
      * inside ``async def`` — always a finding (the loop stalls);
      * ``time.sleep`` in a *sync* def of a module that imports asyncio or
        threading — flagged because sync helpers in async/threaded runtime
        modules get called from coroutines sooner or later; prove the
        helper thread-only and suppress with a reason, or convert.
    """

    id = "DL001"
    name = "blocking-call-in-async"

    @staticmethod
    def _normalize(name: str | None) -> str | None:
        """Canonicalize alias dodges: ``import time as _time`` must not
        evade the matcher (runtime/audit.py used exactly that spelling)."""
        if name is None:
            return None
        parts = [p.lstrip("_") for p in name.split(".")]
        return ".".join(parts)

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            fn = enclosing_function(node)
            in_async = isinstance(fn, ast.AsyncFunctionDef)
            name = self._normalize(dotted(node.func))
            if in_async:
                if name in BLOCKING_CALLS:
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"blocking call {name}() inside async def "
                                f"{fn.name!r} stalls the event loop",
                        hint=BLOCKING_CALLS[name],
                        context=qualname(node), detail=name,
                    )
                elif name == "open":
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"sync file I/O open() inside async def "
                                f"{fn.name!r} can stall the event loop",
                        hint="await asyncio.to_thread(...) for slow/NFS paths, "
                             "or suppress with a reason for tiny local reads",
                        context=qualname(node), detail="open",
                    )
                elif self._untimed_lock_acquire(node):
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message="untimed threading Lock.acquire() inside "
                                f"async def {fn.name!r} can deadlock the loop",
                        hint="acquire(timeout=...) in a thread, or an "
                             "asyncio.Lock",
                        context=qualname(node),
                        detail=f"acquire:{dotted(node.func)}",
                    )
            elif (
                name == "time.sleep"
                and ctx.imports_async_runtime
                and isinstance(fn, ast.FunctionDef)
            ):
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"time.sleep() in sync def {fn.name!r} of an "
                            "asyncio module: loop-reachable unless proven "
                            "thread-only",
                    hint="convert to async + asyncio.sleep, or suppress "
                         "with a thread-only reason",
                    context=qualname(node), detail="time.sleep:sync",
                )

    @staticmethod
    def _untimed_lock_acquire(node: ast.Call) -> bool:
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "acquire"):
            return False
        if isinstance(getattr(node, "_dl_parent", None), ast.Await):
            # ``await lock.acquire()`` is an asyncio lock: it yields, the
            # loop keeps running — holding it across wire latency is
            # DL009's business, not a thread-blocking call
            return False
        recv = dotted(func.value) or ""
        if "lock" not in recv.lower():
            return False
        for kw in node.keywords:
            if kw.arg in ("timeout", "blocking"):
                return False
        return not node.args  # acquire(False) / acquire(timeout) are timed


# --------------------------------------------------------------------------
# DL002 orphaned-task
# --------------------------------------------------------------------------

_SPAWN_ATTRS = {"create_task", "ensure_future"}


def _is_task_spawn(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _SPAWN_ATTRS:
        return True
    return isinstance(func, ast.Name) and func.id in _SPAWN_ATTRS


class OrphanedTask:
    """DL002: ``create_task``/``ensure_future`` result dropped.

    The event loop holds only a *weak* reference to tasks: a spawn whose
    result is discarded can be garbage-collected mid-flight, silently
    cancelling the work — the exact PR-3 drain-task pitfall. Keep a strong
    reference (``runtime.context.spawn`` does, plus crash logging) or chain
    ``.add_done_callback`` directly.
    """

    id = "DL002"
    name = "orphaned-task"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        for node in ctx.nodes:
            call: ast.Call | None = None
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and _is_task_spawn(node.value)
            ):
                call = node.value
            elif (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_task_spawn(node.value)
                and all(
                    isinstance(t, ast.Name) and t.id == "_"
                    for t in node.targets
                )
            ):
                call = node.value
            if call is None:
                continue
            coro = ast.unparse(call.args[0]) if call.args else "?"
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"task for {coro!r} has no strong reference: the "
                        "loop only holds it weakly, so GC can cancel it "
                        "mid-flight",
                hint="use dynamo_tpu.runtime.context.spawn(...) (strong ref "
                     "+ exception logging), or keep the Task yourself",
                context=qualname(node), detail=coro[:80],
            )


# --------------------------------------------------------------------------
# DL003 swallowed-exception
# --------------------------------------------------------------------------

_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}
_HOT_PREFIXES = ("dynamo_tpu/runtime/", "dynamo_tpu/engine/",
                 "dynamo_tpu/frontend/")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(
        isinstance(n, ast.Name) and n.id in ("Exception", "BaseException")
        for n in names
    )


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    """Does this handler raise, log, or otherwise surface what it caught?"""
    exc_name = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            d = dotted(node.func) or ""
            last = d.rsplit(".", 1)[-1]
            recv = d.rsplit(".", 1)[0] if "." in d else ""
            if last in _LOG_METHODS and (
                "log" in recv.lower() or recv == "logging"
            ):
                return True
            if d in ("traceback.print_exc", "traceback.format_exc", "print"):
                return True
        if (
            exc_name
            and isinstance(node, ast.Name)
            and node.id == exc_name
            and isinstance(node.ctx, ast.Load)
        ):
            return True  # the exception value is used (mapped/propagated)
    return False


class SwallowedException:
    """DL003: broad ``except Exception``/bare except that hides the error.

    A handler that neither re-raises, logs, nor uses the caught value turns
    real failures (KV leak, lost migration, dead stream) into silence. Hot
    paths (runtime/, engine/, frontend/) must triage every site; elsewhere
    the committed baseline may grandfather old ones.
    """

    id = "DL003"
    name = "swallowed-exception"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        for node in ctx.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _handler_reports(node):
                continue
            hot = ctx.path.startswith(_HOT_PREFIXES)
            where = "hot path: " if hot else ""
            caught = "bare except" if node.type is None else (
                f"except {ast.unparse(node.type)}"
            )
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"{where}{caught} neither re-raises, logs, nor uses "
                        "the exception — failures vanish silently",
                hint="re-raise, log with context, map to a typed transport "
                     "error, or suppress with the contract reason",
                context=qualname(node),
                detail=f"{caught}:{qualname(node)}",
            )


# --------------------------------------------------------------------------
# DL004 resource-pairing
# --------------------------------------------------------------------------

ACQUIRE_ATTRS = {"alloc_page", "take_prefix", "pull_kv_blocks",
                 "acquire_pages", "export_kv_blocks"}
RELEASE_ATTRS = {"release", "free", "release_kv_blocks", "free_blocks",
                 "release_pages"}


def _in_cleanup(node: ast.AST) -> bool:
    """Is ``node`` inside an except handler or a try/finally finalbody?"""
    child = node
    for p in parents(node):
        if isinstance(p, ast.ExceptHandler):
            return True
        if isinstance(p, ast.Try) and any(
            child is n or any(child is d for d in ast.walk(n))
            for n in p.finalbody
        ):
            return True
        child = p
    return False


def _name_loads(tree: ast.AST, name: str) -> list[ast.Name]:
    return [
        n for n in ast.walk(tree)
        if isinstance(n, ast.Name) and n.id == name
        and isinstance(n.ctx, ast.Load)
    ]


class ResourcePairing:
    """DL004: KV page-pool acquire without a release on every path.

    The PR-3 exported-page leaks were exactly this shape: pages acquired,
    an error path returned early, and the pool bled until the export TTL.
    Function-local and deliberately lightweight: an acquired value that
    *escapes* (returned, yielded, stored into an attribute/container,
    passed to another function) transfers ownership and is not tracked
    further; one that stays local must be released, and released on the
    exception path (finally/except), not just the happy line.
    """

    id = "DL004"
    name = "resource-pairing"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        # acquire sites are rare: find them in one pass over the flat node
        # list, then do the (per-site) function-local trace
        for node in ctx.nodes:
            if not isinstance(node, ast.Assign):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            d = dotted(call.func) or ""
            attr = d.rsplit(".", 1)[-1]
            if attr not in ACQUIRE_ATTRS:
                continue
            if len(node.targets) != 1 or not isinstance(
                node.targets[0], ast.Name
            ):
                continue  # non-name bindings: treated as escaped
            fn = enclosing_function(node)
            if fn is None or isinstance(fn, ast.Lambda):
                continue
            var = node.targets[0].id
            escapes, released, release_safe = self._trace(fn, node, var)
            if escapes:
                continue
            if not released:
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"{attr}() result {var!r} is never released, "
                            "freed, or transferred — the pool leaks",
                    hint=f"release {var!r} (finally:) or hand ownership off",
                    context=qualname(node), detail=f"{attr}:{var}:leak",
                )
            elif not release_safe:
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"{attr}() result {var!r} is only released on "
                            "the happy path — an exception in between "
                            "leaks it",
                    hint="move the release into finally: (or release in "
                         "the except handler before re-raising)",
                    context=qualname(node),
                    detail=f"{attr}:{var}:unsafe-release",
                )

    @staticmethod
    def _trace(fn, acquire_stmt, var) -> tuple[bool, bool, bool]:
        """(escapes, released_anywhere, released_on_exception_path)."""
        escapes = released = release_safe = False
        for node in ast.walk(fn):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                val = node.value
                if val is not None and _name_loads(val, var):
                    escapes = True
            elif isinstance(node, ast.Assign):
                if node is acquire_stmt:
                    continue
                if _name_loads(node.value, var) and any(
                    not isinstance(t, ast.Name) for t in node.targets
                ):
                    escapes = True  # stored into attribute/subscript/tuple
            elif isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                attr = d.rsplit(".", 1)[-1]
                arg_uses = any(
                    _name_loads(a, var)
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                )
                if not arg_uses:
                    # method call ON the var (var.append/…) is fine; a call
                    # on some receiver path containing var isn't ownership
                    continue
                if attr in RELEASE_ATTRS:
                    released = True
                    if _in_cleanup(node):
                        release_safe = True
                else:
                    escapes = True  # passed to arbitrary callee: ownership
                    # ambiguity resolved toward "transferred" (precision
                    # over recall — this rule must stay quiet when unsure)
        if released and not release_safe:
            # a release with nothing raise-capable before it is safe enough:
            # approximate by "release is the lexically next statement"
            nxt = ResourcePairing._next_stmt(fn, acquire_stmt)
            if nxt is not None and any(
                isinstance(n, ast.Call)
                and (dotted(n.func) or "").rsplit(".", 1)[-1] in RELEASE_ATTRS
                and any(_name_loads(a, var) for a in n.args)
                for n in ast.walk(nxt)
            ):
                release_safe = True
        return escapes, released, release_safe

    @staticmethod
    def _next_stmt(fn, stmt):
        for node in ast.walk(fn):
            body = getattr(node, "body", None)
            if isinstance(body, list) and stmt in body:
                i = body.index(stmt)
                if i + 1 < len(body):
                    return body[i + 1]
        return None


# --------------------------------------------------------------------------
# DL005 cross-thread-mutation
# --------------------------------------------------------------------------


class CrossThreadMutation:
    """DL005: the same ``self.attr`` rebound from both the step thread and
    coroutine bodies without lock/queue mediation.

    The engine owns the device from a dedicated step thread
    (``threading.Thread(target=self._thread_loop)``); coroutines run on the
    event loop. An attribute *rebound* (``self.x = ...`` / ``self.x += 1``)
    from both worlds is a data race under kill-9 churn — exactly where
    VERDICT r5 says "step-thread/page-pool races actually live".
    ``__init__`` writes are construction (happens-before the thread start)
    and writes under ``with self.<...lock...>:`` count as mediated.
    Mutating calls on thread-safe objects (``.set()``, ``.put_nowait()``)
    are intentionally out of scope — rebinding is the hazard this catches.
    """

    id = "DL005"
    name = "cross-thread-mutation"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        if "Thread" not in ctx.source:
            return  # no worker threads here: nothing to race with
        for node in ctx.nodes:
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx, cls) -> Iterable[Finding]:
        methods: dict[str, ast.AST] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = stmt

        thread_entries = self._thread_targets(cls, methods)
        if not thread_entries:
            return

        calls = {
            name: self._self_calls(node) for name, node in methods.items()
        }
        thread_world = self._closure(thread_entries, calls, methods)
        async_roots = {
            n for n, m in methods.items()
            if isinstance(m, ast.AsyncFunctionDef)
        }
        async_world = self._closure(async_roots, calls, methods)

        def writes(world: set[str]) -> dict[str, list[tuple[str, ast.AST]]]:
            out: dict[str, list[tuple[str, ast.AST]]] = {}
            for name in world:
                if name == "__init__":
                    continue
                for attr, node in self._attr_writes(methods[name]):
                    out.setdefault(attr, []).append((name, node))
            return out

        # the dynarace registry (mirrored in catalog.SHARED_STATE) keyed
        # by attribute suffix: a flagged attr that is tracked dynamically
        # cites its documented discipline in the finding
        tracked = {
            key.rsplit(".", 1)[-1]: (key, desc)
            for key, desc in ctx.catalog.SHARED_STATE.items()
        }
        tw, aw = writes(thread_world), writes(async_world)
        for attr in sorted(set(tw) & set(aw)):
            a_method, a_node = aw[attr][0]
            t_method = tw[attr][0][0]
            message = (
                f"self.{attr} rebound from both the step thread "
                f"({t_method}) and a coroutine ({a_method}) with "
                "no lock/queue mediation"
            )
            if attr.lstrip("_") in tracked:
                key, desc = tracked[attr.lstrip("_")]
                message += (
                    f" (dynarace-tracked as {key!r}: {desc})"
                )
            yield Finding(
                rule=self.id, path=ctx.path,
                line=a_node.lineno, col=a_node.col_offset,
                message=message,
                hint="route one side through a queue/call_soon_threadsafe, "
                     "guard both with a lock, or make one side read-only",
                context=f"{cls.name}", detail=attr,
            )

    @staticmethod
    def _thread_targets(cls, methods) -> set[str]:
        """Methods used as ``threading.Thread(target=self.X)`` anywhere in
        the class (the step/writer threads)."""
        out: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            if d.rsplit(".", 1)[-1] != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Attribute):
                    if (
                        isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"
                        and kw.value.attr in methods
                    ):
                        out.add(kw.value.attr)
        return out

    @staticmethod
    def _self_calls(method) -> set[str]:
        return {
            n.func.attr
            for n in ast.walk(method)
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == "self"
        }

    @staticmethod
    def _closure(roots: set[str], calls, methods) -> set[str]:
        seen = set()
        frontier = [r for r in roots if r in methods]
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            for callee in calls.get(cur, ()):
                if callee in methods and callee not in seen:
                    # only sync helpers propagate; an async callee from a
                    # thread method would be a bug of its own
                    if not isinstance(methods[callee], ast.AsyncFunctionDef):
                        frontier.append(callee)
        return seen

    @staticmethod
    def _attr_writes(method) -> Iterable[tuple[str, ast.AST]]:
        for node in ast.walk(method):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and not CrossThreadMutation._under_lock(node)
                ):
                    yield t.attr, node

    @staticmethod
    def _under_lock(node: ast.AST) -> bool:
        for p in parents(node):
            if isinstance(p, (ast.With, ast.AsyncWith)):
                for item in p.items:
                    src = ""
                    try:
                        src = ast.unparse(item.context_expr)
                    # dynalint: disable=DL003 -- defensive: an unparse
                    # failure just means "not a lock expr"; there is
                    # nothing to report and no value to use
                    except Exception:  # pragma: no cover - defensive
                        pass
                    if "lock" in src.lower():
                        return True
        return False


# --------------------------------------------------------------------------
# DL006 fault-site / metric registry
# --------------------------------------------------------------------------

_FIRE_ATTRS = {"fire", "fire_sync", "check", "fire_link", "link_blocked"}
# corruption injectors (runtime/faults.py corrupt_bytes, runtime/
# integrity.py corrupt_token_ids): same site-literal contract as fire —
# a typo'd site means the chaos schedule flips no bits and tests nothing
_CORRUPT_FNS = {"corrupt_bytes", "corrupt_token_ids"}
_METRIC_ATTRS = {"counter", "gauge", "histogram"}
# tracing span emitters (runtime/tracing.py): with tracing.span("...")
# context managers and explicit tracing.emit_span("...") emissions
_SPAN_ATTRS = {"span", "emit_span"}


class FaultSiteRegistry:
    """DL006: fault-injection sites and metric names must come from the
    committed catalog (tools/dynalint/catalog.py).

    A ``FAULTS.fire("typo.site")`` never trips — the chaos schedule that
    names the real site silently tests nothing, and a replayed
    ``DYN_FAULTS`` spec stops matching the code it was recorded against.
    Same for metric names: a renamed counter orphans every dashboard and
    alert pointing at the old name. The catalog is the reviewable,
    diffable registry; the runner also warns about *stale* entries no code
    uses any more.
    """

    id = "DL006"
    name = "fault-site-registry"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        fault_sites = set(ctx.catalog.FAULT_SITES)
        metric_names = set(ctx.catalog.METRIC_NAMES)
        span_names = set(getattr(ctx.catalog, "SPAN_NAMES", ()))
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Name)
                and func.id in _SPAN_ATTRS
                and node.args
            ):
                # from-imported span()/emit_span()
                yield from self._check_span(ctx, node, span_names)
                continue
            if (
                isinstance(func, ast.Name)
                and func.id in _CORRUPT_FNS
                and node.args
            ):
                # from-imported corrupt_token_ids()/corrupt_bytes()
                yield from self._check_site(ctx, node, fault_sites)
                continue
            if not isinstance(func, ast.Attribute):
                continue
            recv = dotted(func.value) or ""
            if (
                func.attr in _FIRE_ATTRS or func.attr in _CORRUPT_FNS
            ) and "faults" in recv.lower():
                yield from self._check_site(ctx, node, fault_sites)
            elif func.attr in _METRIC_ATTRS and node.args:
                yield from self._check_metric(ctx, node, metric_names)
            elif (
                func.attr in _SPAN_ATTRS
                and "tracing" in recv.lower()
                and node.args
            ):
                yield from self._check_span(ctx, node, span_names)

    def _check_site(self, ctx, node, known) -> Iterable[Finding]:
        if not node.args:
            return
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message="fault site must be a string literal (dynamic site "
                        "names can't be catalogued or replayed)",
                hint="inline the site string",
                context=qualname(node), detail="dynamic-site",
            )
            return
        site = arg.value
        ctx.used_fault_sites.add(site)
        if site not in known:
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"fault site {site!r} is not in the documented "
                        "catalog — chaos schedules naming it silently drift",
                hint="add it to tools/dynalint/catalog.py FAULT_SITES (and "
                     "runtime/faults.py KNOWN_SITES) or fix the typo",
                context=qualname(node), detail=f"site:{site}",
            )

    def _check_metric(self, ctx, node, known) -> Iterable[Finding]:
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message="metric name must be a string literal so dashboards "
                        "and the catalog can reference it",
                hint="inline the metric name",
                context=qualname(node), detail="dynamic-metric",
            )
            return
        name = arg.value
        ctx.used_metric_names.add(name)
        if name not in known:
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"metric {name!r} is not registered in the catalog "
                        "— renames orphan dashboards/alerts silently",
                hint="add it to tools/dynalint/catalog.py METRIC_NAMES or "
                     "fix the typo",
                context=qualname(node), detail=f"metric:{name}",
            )

    def _check_span(self, ctx, node, known) -> Iterable[Finding]:
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message="span name must be a string literal so trace "
                        "dashboards and the catalog can reference it",
                hint="inline the span name",
                context=qualname(node), detail="dynamic-span",
            )
            return
        name = arg.value
        ctx.used_span_names.add(name)
        if name not in known:
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"span {name!r} is not in the documented catalog "
                        "— trace queries and the e2e span assertions "
                        "reference catalogued names only",
                hint="add it to tools/dynalint/catalog.py SPAN_NAMES or "
                     "fix the typo",
                context=qualname(node), detail=f"span:{name}",
            )


# --------------------------------------------------------------------------
# DL007 wire-schema drift
# --------------------------------------------------------------------------


class WireSchemaDrift:
    """DL007: cross-process wire-schema drift.

    The hub protocol, the worker admin RPC, and the transfer-plane control
    ops exist only by convention (string op names + dict fields). This
    rule extracts every client-side emission and every server-side
    dispatch branch project-wide (tools/dynalint/wire.py) and fails on an
    op or field that is sent but unhandled, a transport err code no client
    maps, a lost dispatcher anchor, or drift against the committed
    ``wire_schema.json`` catalog — the machine-checked stand-in for the
    reference's shared Rust protocol structs. Never baselineable.
    """

    id = "DL007"
    name = "wire-schema-drift"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        return ()  # project-level rule: see check_project

    def check_project(self, project: ProjectIndex) -> Iterable[Finding]:
        from tools.dynalint import wire

        yield from wire.check_project(project)


# --------------------------------------------------------------------------
# DL008 deadline-taint
# --------------------------------------------------------------------------

# receiving a request context and then NOT passing it along breaks the
# end-to-end deadline contract (PR 3): the callee runs unbounded while the
# frontend's 504 fires without cancelling the work


class DeadlineTaint:
    """DL008: request-path function has a Context/deadline in scope but
    drops it.

    Three shapes, all of which silently detach a stage from the
    end-to-end deadline (the class behind the PR 3 migration-retry
    hardening):

      * a call to a context-accepting callee (any project function with a
        ``context`` / ``x: Context`` parameter, via the project index)
        that forwards neither the in-scope context nor a ``.child()`` of
        it;
      * a fresh ``Context()`` constructed while a request context is in
        scope (the new context has no deadline — derive with
        ``context.child()`` or pass ``deadline=`` explicitly);
      * a ``{"kind": "req"}`` wire frame whose headers don't come from
        ``context.wire_headers()`` (the only thing that attaches
        DEADLINE_HEADER);
      * a ROOT ``Context()`` minted without ``deadline=`` in a serving
        surface (frontend/gateway/grpc/multimodal) — these are where the
        end-to-end budget is supposed to START (the HTTP frontend's
        DYN_REQUEST_TIMEOUT_S contract); a deadline-less root here means
        every downstream stage runs unbounded.
    """

    id = "DL008"
    name = "deadline-taint"

    # modules where requests ENTER the system: roots minted here must
    # carry the end-to-end budget
    SERVING_SURFACES = (
        "dynamo_tpu/frontend/", "dynamo_tpu/gateway/",
        "dynamo_tpu/grpc/", "dynamo_tpu/multimodal/",
    )

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        project = ctx.project
        if project is None:
            return
        yield from self._check_serving_roots(ctx, project)
        callees = project.context_callee_names
        for info in project.functions.values():
            if info.path != ctx.path or not info.has_request_context:
                continue
            tainted = {
                a.arg for a in (
                    *info.node.args.posonlyargs, *info.node.args.args,
                    *info.node.args.kwonlyargs,
                )
                if a.arg == "context" or a.arg in self._annotated_ctx(info)
            }
            tainted |= self._child_aliases(info.node, tainted)
            # names bound from a fresh Context(...): passing one IS
            # passing a context (the fresh-Context finding below already
            # covers the deadline loss — don't double-report the call)
            ctx_locals = {
                n.targets[0].id
                for n in ast.walk(info.node)
                if isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)
                and (dotted(n.value.func) or "").rsplit(".", 1)[-1]
                == "Context"
            }
            for name, call in info.calls:
                last = name.rsplit(".", 1)[-1]
                root = name.split(".", 1)[0]
                if root in tainted:
                    continue  # context.child() / context.remaining_s()
                arg_names = self._loaded_names(call)
                if last == "Context":
                    if not any(kw.arg == "deadline" for kw in call.keywords):
                        yield Finding(
                            rule=self.id, path=ctx.path,
                            line=call.lineno, col=call.col_offset,
                            message="fresh Context() constructed while a "
                                    "request context is in scope — the new "
                                    "context carries NO deadline",
                            hint="derive it: context.child(), or pass "
                                 "deadline=context.deadline explicitly",
                            context=info.qualname,
                            detail=f"fresh-context:{info.qualname}",
                        )
                    continue
                # the bare-name prefilter is cheap; context_accepting
                # then applies the unanimity rule (every project def of
                # the name takes a context) so an unrelated same-named
                # callee can't smear findings onto innocent calls
                if (
                    last in callees and last != "child"
                    and project.context_accepting(info, name)
                ):
                    if arg_names & (tainted | ctx_locals):
                        continue
                    if any(
                        isinstance(a, ast.Call)
                        and (dotted(a.func) or "").rsplit(".", 1)[-1]
                        in ("Context", "ensure_context")
                        for a in call.args
                    ):
                        continue  # inline Context(...): reported above
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=call.lineno, col=call.col_offset,
                        message=f"{last}() accepts a request context but "
                                "this call forwards none — the deadline "
                                "(and cancellation) chain breaks here",
                        hint="pass the in-scope context (or "
                             "context.child() for a sub-request)",
                        context=info.qualname,
                        detail=f"drop:{info.qualname}:{last}",
                    )
            yield from self._check_req_frames(ctx, info, tainted)

    def _check_serving_roots(
        self, ctx: ScanContext, project: ProjectIndex
    ) -> Iterable[Finding]:
        if not ctx.path.startswith(self.SERVING_SURFACES):
            return
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            if (dotted(node.func) or "").rsplit(".", 1)[-1] != "Context":
                continue
            if any(kw.arg == "deadline" for kw in node.keywords):
                continue
            info = project.function_at(ctx.path, node)
            if info is not None and info.has_request_context:
                continue  # the fresh-Context check above owns this case
            fn_name = info.qualname if info else "<module>"
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message="root Context() minted on a serving surface "
                        "without a deadline — every downstream stage of "
                        "this request runs unbounded (the "
                        "DYN_REQUEST_TIMEOUT_S contract starts HERE)",
                hint="Context(..., deadline=time.monotonic() + budget_s) "
                     "— mirror HttpFrontend._traced_context",
                context=fn_name, detail=f"root-context:{fn_name}",
            )

    @staticmethod
    def _annotated_ctx(info) -> set[str]:
        out = set()
        for a in (
            *info.node.args.posonlyargs, *info.node.args.args,
            *info.node.args.kwonlyargs,
        ):
            ann = a.annotation
            if ann is None:
                continue
            # same resolution as core._is_request_context_param: dotted
            # OR string annotation ('c: "Context"') — diverging here
            # would flag every correct forward in such a function
            ann_name = dotted(ann) or (
                ann.value if isinstance(ann, ast.Constant)
                and isinstance(ann.value, str) else ""
            )
            if (ann_name or "").rsplit(".", 1)[-1] == "Context":
                out.add(a.arg)
        return out

    @staticmethod
    def _child_aliases(fn, tainted: set[str]) -> set[str]:
        """Names bound from ``<tainted>.child(...)`` carry the deadline."""
        out: set[str] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            v = node.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "child"
                and (dotted(v.func.value) or "").split(".", 1)[0] in tainted
            ):
                out.add(node.targets[0].id)
        return out

    @staticmethod
    def _loaded_names(call: ast.Call) -> set[str]:
        out: set[str] = set()
        for a in (*call.args, *[kw.value for kw in call.keywords]):
            for n in ast.walk(a):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                    out.add(n.id)
        return out

    def _check_req_frames(self, ctx, info, tainted) -> Iterable[Finding]:
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Dict):
                continue
            keys = {
                k.value: v for k, v in zip(node.keys, node.values)
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
            kind = keys.get("kind")
            if not (
                isinstance(kind, ast.Constant) and kind.value == "req"
            ):
                continue
            headers = keys.get("headers")
            ok = (
                isinstance(headers, ast.Call)
                and isinstance(headers.func, ast.Attribute)
                and headers.func.attr == "wire_headers"
            )
            if not ok:
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message="request frame sent without "
                            "context.wire_headers() — DEADLINE_HEADER is "
                            "dropped at this hop, downstream runs "
                            "unbounded",
                    hint='"headers": context.wire_headers()',
                    context=info.qualname,
                    detail=f"req-headers:{info.qualname}",
                )


# --------------------------------------------------------------------------
# DL009 lock-across-await
# --------------------------------------------------------------------------


class LockAcrossAwait:
    """DL009: an async lock span awaits a wire- or blocking-tagged call.

    ``async with lock:`` (or an untimed ``await lock.acquire()`` span)
    whose body awaits something that can stall on the network, a thread
    pool, or a sleep holds every other coroutine contending that lock for
    the full stall — the hub write path serializing behind one slow peer
    is exactly how a single wedged follower turns into cluster-wide
    backpressure. Wire-taint is computed transitively over the project
    call graph (a helper that dials is as tagged as the dial itself).
    Deliberate serialization points (per-connection frame writers) get a
    reasoned suppression, which is the point: the contract is written
    down where the lock is held.
    """

    id = "DL009"
    name = "lock-across-await"

    _EXTRA_TAGGED = frozenset({"to_thread", "run_in_executor"})

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        project = ctx.project
        if project is None:
            return
        for node in ctx.nodes:
            if isinstance(node, ast.AsyncWith):
                lock_src = self._lock_src(node)
                if lock_src is None:
                    continue
                hit = self._first_tagged_await(
                    project, ctx, node.body
                )
                if hit is not None:
                    call_name, line = hit
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"async with {lock_src}: body awaits "
                                f"{call_name}() (line {line}) — every "
                                "contender stalls for the full wire/"
                                "blocking latency",
                        hint="move the slow await outside the lock, "
                             "snapshot state under the lock and act "
                             "after, or suppress with the serialization "
                             "contract as the reason",
                        context=qualname(node),
                        detail=f"{lock_src}:{call_name}",
                    )
            elif isinstance(node, ast.Await):
                yield from self._check_acquire_span(project, ctx, node)

    @staticmethod
    def _lock_src(node: ast.AsyncWith) -> str | None:
        for item in node.items:
            try:
                src = ast.unparse(item.context_expr)
            # dynalint: disable=DL003 -- defensive: an unparse failure
            # just means "not a lock expr"; nothing to report
            except Exception:  # pragma: no cover - defensive
                continue
            if "lock" in src.lower():
                return src
        return None

    def _first_tagged_await(
        self, project, ctx, body
    ) -> tuple[str, int] | None:
        for stmt in body:
            for sub in ast.walk(stmt):
                if not (
                    isinstance(sub, ast.Await)
                    and isinstance(sub.value, ast.Call)
                ):
                    continue
                name = dotted(sub.value.func) or ""
                if self._tagged(project, ctx, sub.value, name):
                    return name, sub.lineno
        return None

    def _tagged(self, project, ctx, call: ast.Call, name: str) -> bool:
        last = name.rsplit(".", 1)[-1]
        if last in self._EXTRA_TAGGED:
            return True
        if name == "asyncio.sleep":
            # sleeping under a lock is a held-lock delay, except the
            # bare yield idiom sleep(0)
            arg = call.args[0] if call.args else None
            return not (
                isinstance(arg, ast.Constant) and arg.value in (0, 0.0)
            )
        caller = project.function_at(ctx.path, call)
        return project.is_wire_call(caller, name)

    def _check_acquire_span(self, project, ctx, node: ast.Await):
        call = node.value
        if not (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
        ):
            return
        recv = dotted(call.func.value) or ""
        if "lock" not in recv.lower():
            return
        if call.args or any(
            kw.arg in ("timeout", "blocking") for kw in call.keywords
        ):
            return
        # span: statements after the acquire up to release() on the same
        # receiver (or end of the enclosing body)
        stmt: ast.AST = node
        for p in parents(node):
            body = getattr(p, "body", None)
            if isinstance(body, list) and any(
                stmt is s or any(stmt is w for w in ast.walk(s))
                for s in body
            ):
                idx = next(
                    i for i, s in enumerate(body)
                    if stmt is s or any(stmt is w for w in ast.walk(s))
                )
                span = []
                for s in body[idx + 1:]:
                    if any(
                        isinstance(w, ast.Call)
                        and isinstance(w.func, ast.Attribute)
                        and w.func.attr == "release"
                        and dotted(w.func.value) == recv
                        for w in ast.walk(s)
                    ):
                        break
                    span.append(s)
                hit = self._first_tagged_await(project, ctx, span)
                if hit is not None:
                    call_name, line = hit
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"untimed {recv}.acquire() span awaits "
                                f"{call_name}() (line {line}) before "
                                "release — contenders stall for the full "
                                "wire/blocking latency",
                        hint="use 'async with' + move the slow await out, "
                             "or suppress with the serialization contract",
                        context=qualname(node),
                        detail=f"acquire:{recv}:{call_name}",
                    )
                return


from tools.dynalint.jaxrules import (  # noqa: E402 - rules need Finding etc.
    DonationAudit,
    HostSyncInHotPath,
    LockDiscipline,
    RetraceHazard,
    SilentFallback,
    SpecCoverage,
)

RULES = {
    r.id: r
    for r in (
        BlockingCallInAsync(),
        OrphanedTask(),
        SwallowedException(),
        ResourcePairing(),
        CrossThreadMutation(),
        FaultSiteRegistry(),
        WireSchemaDrift(),
        DeadlineTaint(),
        LockAcrossAwait(),
        HostSyncInHotPath(),
        RetraceHazard(),
        DonationAudit(),
        SpecCoverage(),
        SilentFallback(),
        LockDiscipline(),
    )
}

# rules that run ONCE over the whole ProjectIndex instead of per file
PROJECT_RULES = ("DL007", "DL015")
