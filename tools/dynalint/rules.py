"""The dynalint rule set (DL001–DL006).

Each rule encodes an invariant this repo has already paid for in bugs
(see tools/dynalint/README.md for the incident each rule back-references).
Rules are pure-AST ``check(ctx) -> list[Finding]`` callables over one file;
DL006 additionally feeds the runner's cross-file stale-catalog check.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.dynalint.core import (
    Finding,
    ScanContext,
    dotted,
    enclosing_function,
    parents,
    qualname,
)

# --------------------------------------------------------------------------
# DL001 blocking-call-in-async
# --------------------------------------------------------------------------

# Calls that park the calling OS thread. Inside ``async def`` they park the
# event loop itself: every in-flight stream on this process stalls behind
# them (the TTFT-tail failure mode PR 3 hand-fixed in the engine).
BLOCKING_CALLS = {
    "time.sleep": "await asyncio.sleep(...)",
    "subprocess.run": "await asyncio.create_subprocess_exec(...)",
    "subprocess.call": "await asyncio.create_subprocess_exec(...)",
    "subprocess.check_call": "await asyncio.create_subprocess_exec(...)",
    "subprocess.check_output": "await asyncio.create_subprocess_exec(...)",
    "os.system": "await asyncio.create_subprocess_shell(...)",
    "urllib.request.urlopen": "await asyncio.to_thread(urllib.request.urlopen, ...)",
    "socket.create_connection": "await asyncio.open_connection(...)",
    "requests.get": "aiohttp / asyncio.to_thread",
    "requests.post": "aiohttp / asyncio.to_thread",
    "requests.put": "aiohttp / asyncio.to_thread",
    "requests.delete": "aiohttp / asyncio.to_thread",
    "requests.head": "aiohttp / asyncio.to_thread",
    "requests.request": "aiohttp / asyncio.to_thread",
}


class BlockingCallInAsync:
    """DL001: blocking call reachable from the event loop.

    Two tiers:
      * inside ``async def`` — always a finding (the loop stalls);
      * ``time.sleep`` in a *sync* def of a module that imports asyncio or
        threading — flagged because sync helpers in async/threaded runtime
        modules get called from coroutines sooner or later; prove the
        helper thread-only and suppress with a reason, or convert.
    """

    id = "DL001"
    name = "blocking-call-in-async"

    @staticmethod
    def _normalize(name: str | None) -> str | None:
        """Canonicalize alias dodges: ``import time as _time`` must not
        evade the matcher (runtime/audit.py used exactly that spelling)."""
        if name is None:
            return None
        parts = [p.lstrip("_") for p in name.split(".")]
        return ".".join(parts)

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            fn = enclosing_function(node)
            in_async = isinstance(fn, ast.AsyncFunctionDef)
            name = self._normalize(dotted(node.func))
            if in_async:
                if name in BLOCKING_CALLS:
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"blocking call {name}() inside async def "
                                f"{fn.name!r} stalls the event loop",
                        hint=BLOCKING_CALLS[name],
                        context=qualname(node), detail=name,
                    )
                elif name == "open":
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message=f"sync file I/O open() inside async def "
                                f"{fn.name!r} can stall the event loop",
                        hint="await asyncio.to_thread(...) for slow/NFS paths, "
                             "or suppress with a reason for tiny local reads",
                        context=qualname(node), detail="open",
                    )
                elif self._untimed_lock_acquire(node):
                    yield Finding(
                        rule=self.id, path=ctx.path,
                        line=node.lineno, col=node.col_offset,
                        message="untimed threading Lock.acquire() inside "
                                f"async def {fn.name!r} can deadlock the loop",
                        hint="acquire(timeout=...) in a thread, or an "
                             "asyncio.Lock",
                        context=qualname(node),
                        detail=f"acquire:{dotted(node.func)}",
                    )
            elif (
                name == "time.sleep"
                and ctx.imports_async_runtime
                and isinstance(fn, ast.FunctionDef)
            ):
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"time.sleep() in sync def {fn.name!r} of an "
                            "asyncio module: loop-reachable unless proven "
                            "thread-only",
                    hint="convert to async + asyncio.sleep, or suppress "
                         "with a thread-only reason",
                    context=qualname(node), detail="time.sleep:sync",
                )

    @staticmethod
    def _untimed_lock_acquire(node: ast.Call) -> bool:
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "acquire"):
            return False
        recv = dotted(func.value) or ""
        if "lock" not in recv.lower():
            return False
        for kw in node.keywords:
            if kw.arg in ("timeout", "blocking"):
                return False
        return not node.args  # acquire(False) / acquire(timeout) are timed


# --------------------------------------------------------------------------
# DL002 orphaned-task
# --------------------------------------------------------------------------

_SPAWN_ATTRS = {"create_task", "ensure_future"}


def _is_task_spawn(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _SPAWN_ATTRS:
        return True
    return isinstance(func, ast.Name) and func.id in _SPAWN_ATTRS


class OrphanedTask:
    """DL002: ``create_task``/``ensure_future`` result dropped.

    The event loop holds only a *weak* reference to tasks: a spawn whose
    result is discarded can be garbage-collected mid-flight, silently
    cancelling the work — the exact PR-3 drain-task pitfall. Keep a strong
    reference (``runtime.context.spawn`` does, plus crash logging) or chain
    ``.add_done_callback`` directly.
    """

    id = "DL002"
    name = "orphaned-task"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        for node in ctx.nodes:
            call: ast.Call | None = None
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and _is_task_spawn(node.value)
            ):
                call = node.value
            elif (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_task_spawn(node.value)
                and all(
                    isinstance(t, ast.Name) and t.id == "_"
                    for t in node.targets
                )
            ):
                call = node.value
            if call is None:
                continue
            coro = ast.unparse(call.args[0]) if call.args else "?"
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"task for {coro!r} has no strong reference: the "
                        "loop only holds it weakly, so GC can cancel it "
                        "mid-flight",
                hint="use dynamo_tpu.runtime.context.spawn(...) (strong ref "
                     "+ exception logging), or keep the Task yourself",
                context=qualname(node), detail=coro[:80],
            )


# --------------------------------------------------------------------------
# DL003 swallowed-exception
# --------------------------------------------------------------------------

_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}
_HOT_PREFIXES = ("dynamo_tpu/runtime/", "dynamo_tpu/engine/",
                 "dynamo_tpu/frontend/")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(
        isinstance(n, ast.Name) and n.id in ("Exception", "BaseException")
        for n in names
    )


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    """Does this handler raise, log, or otherwise surface what it caught?"""
    exc_name = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            d = dotted(node.func) or ""
            last = d.rsplit(".", 1)[-1]
            recv = d.rsplit(".", 1)[0] if "." in d else ""
            if last in _LOG_METHODS and (
                "log" in recv.lower() or recv == "logging"
            ):
                return True
            if d in ("traceback.print_exc", "traceback.format_exc", "print"):
                return True
        if (
            exc_name
            and isinstance(node, ast.Name)
            and node.id == exc_name
            and isinstance(node.ctx, ast.Load)
        ):
            return True  # the exception value is used (mapped/propagated)
    return False


class SwallowedException:
    """DL003: broad ``except Exception``/bare except that hides the error.

    A handler that neither re-raises, logs, nor uses the caught value turns
    real failures (KV leak, lost migration, dead stream) into silence. Hot
    paths (runtime/, engine/, frontend/) must triage every site; elsewhere
    the committed baseline may grandfather old ones.
    """

    id = "DL003"
    name = "swallowed-exception"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        for node in ctx.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _handler_reports(node):
                continue
            hot = ctx.path.startswith(_HOT_PREFIXES)
            where = "hot path: " if hot else ""
            caught = "bare except" if node.type is None else (
                f"except {ast.unparse(node.type)}"
            )
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"{where}{caught} neither re-raises, logs, nor uses "
                        "the exception — failures vanish silently",
                hint="re-raise, log with context, map to a typed transport "
                     "error, or suppress with the contract reason",
                context=qualname(node),
                detail=f"{caught}:{qualname(node)}",
            )


# --------------------------------------------------------------------------
# DL004 resource-pairing
# --------------------------------------------------------------------------

ACQUIRE_ATTRS = {"alloc_page", "take_prefix", "pull_kv_blocks",
                 "acquire_pages", "export_kv_blocks"}
RELEASE_ATTRS = {"release", "free", "release_kv_blocks", "free_blocks",
                 "release_pages"}


def _in_cleanup(node: ast.AST) -> bool:
    """Is ``node`` inside an except handler or a try/finally finalbody?"""
    child = node
    for p in parents(node):
        if isinstance(p, ast.ExceptHandler):
            return True
        if isinstance(p, ast.Try) and any(
            child is n or any(child is d for d in ast.walk(n))
            for n in p.finalbody
        ):
            return True
        child = p
    return False


def _name_loads(tree: ast.AST, name: str) -> list[ast.Name]:
    return [
        n for n in ast.walk(tree)
        if isinstance(n, ast.Name) and n.id == name
        and isinstance(n.ctx, ast.Load)
    ]


class ResourcePairing:
    """DL004: KV page-pool acquire without a release on every path.

    The PR-3 exported-page leaks were exactly this shape: pages acquired,
    an error path returned early, and the pool bled until the export TTL.
    Function-local and deliberately lightweight: an acquired value that
    *escapes* (returned, yielded, stored into an attribute/container,
    passed to another function) transfers ownership and is not tracked
    further; one that stays local must be released, and released on the
    exception path (finally/except), not just the happy line.
    """

    id = "DL004"
    name = "resource-pairing"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        # acquire sites are rare: find them in one pass over the flat node
        # list, then do the (per-site) function-local trace
        for node in ctx.nodes:
            if not isinstance(node, ast.Assign):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            d = dotted(call.func) or ""
            attr = d.rsplit(".", 1)[-1]
            if attr not in ACQUIRE_ATTRS:
                continue
            if len(node.targets) != 1 or not isinstance(
                node.targets[0], ast.Name
            ):
                continue  # non-name bindings: treated as escaped
            fn = enclosing_function(node)
            if fn is None or isinstance(fn, ast.Lambda):
                continue
            var = node.targets[0].id
            escapes, released, release_safe = self._trace(fn, node, var)
            if escapes:
                continue
            if not released:
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"{attr}() result {var!r} is never released, "
                            "freed, or transferred — the pool leaks",
                    hint=f"release {var!r} (finally:) or hand ownership off",
                    context=qualname(node), detail=f"{attr}:{var}:leak",
                )
            elif not release_safe:
                yield Finding(
                    rule=self.id, path=ctx.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"{attr}() result {var!r} is only released on "
                            "the happy path — an exception in between "
                            "leaks it",
                    hint="move the release into finally: (or release in "
                         "the except handler before re-raising)",
                    context=qualname(node),
                    detail=f"{attr}:{var}:unsafe-release",
                )

    @staticmethod
    def _trace(fn, acquire_stmt, var) -> tuple[bool, bool, bool]:
        """(escapes, released_anywhere, released_on_exception_path)."""
        escapes = released = release_safe = False
        for node in ast.walk(fn):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                val = node.value
                if val is not None and _name_loads(val, var):
                    escapes = True
            elif isinstance(node, ast.Assign):
                if node is acquire_stmt:
                    continue
                if _name_loads(node.value, var) and any(
                    not isinstance(t, ast.Name) for t in node.targets
                ):
                    escapes = True  # stored into attribute/subscript/tuple
            elif isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                attr = d.rsplit(".", 1)[-1]
                arg_uses = any(
                    _name_loads(a, var)
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                )
                if not arg_uses:
                    # method call ON the var (var.append/…) is fine; a call
                    # on some receiver path containing var isn't ownership
                    continue
                if attr in RELEASE_ATTRS:
                    released = True
                    if _in_cleanup(node):
                        release_safe = True
                else:
                    escapes = True  # passed to arbitrary callee: ownership
                    # ambiguity resolved toward "transferred" (precision
                    # over recall — this rule must stay quiet when unsure)
        if released and not release_safe:
            # a release with nothing raise-capable before it is safe enough:
            # approximate by "release is the lexically next statement"
            nxt = ResourcePairing._next_stmt(fn, acquire_stmt)
            if nxt is not None and any(
                isinstance(n, ast.Call)
                and (dotted(n.func) or "").rsplit(".", 1)[-1] in RELEASE_ATTRS
                and any(_name_loads(a, var) for a in n.args)
                for n in ast.walk(nxt)
            ):
                release_safe = True
        return escapes, released, release_safe

    @staticmethod
    def _next_stmt(fn, stmt):
        for node in ast.walk(fn):
            body = getattr(node, "body", None)
            if isinstance(body, list) and stmt in body:
                i = body.index(stmt)
                if i + 1 < len(body):
                    return body[i + 1]
        return None


# --------------------------------------------------------------------------
# DL005 cross-thread-mutation
# --------------------------------------------------------------------------


class CrossThreadMutation:
    """DL005: the same ``self.attr`` rebound from both the step thread and
    coroutine bodies without lock/queue mediation.

    The engine owns the device from a dedicated step thread
    (``threading.Thread(target=self._thread_loop)``); coroutines run on the
    event loop. An attribute *rebound* (``self.x = ...`` / ``self.x += 1``)
    from both worlds is a data race under kill-9 churn — exactly where
    VERDICT r5 says "step-thread/page-pool races actually live".
    ``__init__`` writes are construction (happens-before the thread start)
    and writes under ``with self.<...lock...>:`` count as mediated.
    Mutating calls on thread-safe objects (``.set()``, ``.put_nowait()``)
    are intentionally out of scope — rebinding is the hazard this catches.
    """

    id = "DL005"
    name = "cross-thread-mutation"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        if "Thread" not in ctx.source:
            return  # no worker threads here: nothing to race with
        for node in ctx.nodes:
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx, cls) -> Iterable[Finding]:
        methods: dict[str, ast.AST] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = stmt

        thread_entries = self._thread_targets(cls, methods)
        if not thread_entries:
            return

        calls = {
            name: self._self_calls(node) for name, node in methods.items()
        }
        thread_world = self._closure(thread_entries, calls, methods)
        async_roots = {
            n for n, m in methods.items()
            if isinstance(m, ast.AsyncFunctionDef)
        }
        async_world = self._closure(async_roots, calls, methods)

        def writes(world: set[str]) -> dict[str, list[tuple[str, ast.AST]]]:
            out: dict[str, list[tuple[str, ast.AST]]] = {}
            for name in world:
                if name == "__init__":
                    continue
                for attr, node in self._attr_writes(methods[name]):
                    out.setdefault(attr, []).append((name, node))
            return out

        tw, aw = writes(thread_world), writes(async_world)
        for attr in sorted(set(tw) & set(aw)):
            a_method, a_node = aw[attr][0]
            t_method = tw[attr][0][0]
            yield Finding(
                rule=self.id, path=ctx.path,
                line=a_node.lineno, col=a_node.col_offset,
                message=f"self.{attr} rebound from both the step thread "
                        f"({t_method}) and a coroutine ({a_method}) with "
                        "no lock/queue mediation",
                hint="route one side through a queue/call_soon_threadsafe, "
                     "guard both with a lock, or make one side read-only",
                context=f"{cls.name}", detail=attr,
            )

    @staticmethod
    def _thread_targets(cls, methods) -> set[str]:
        """Methods used as ``threading.Thread(target=self.X)`` anywhere in
        the class (the step/writer threads)."""
        out: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            if d.rsplit(".", 1)[-1] != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Attribute):
                    if (
                        isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"
                        and kw.value.attr in methods
                    ):
                        out.add(kw.value.attr)
        return out

    @staticmethod
    def _self_calls(method) -> set[str]:
        return {
            n.func.attr
            for n in ast.walk(method)
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == "self"
        }

    @staticmethod
    def _closure(roots: set[str], calls, methods) -> set[str]:
        seen = set()
        frontier = [r for r in roots if r in methods]
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            for callee in calls.get(cur, ()):
                if callee in methods and callee not in seen:
                    # only sync helpers propagate; an async callee from a
                    # thread method would be a bug of its own
                    if not isinstance(methods[callee], ast.AsyncFunctionDef):
                        frontier.append(callee)
        return seen

    @staticmethod
    def _attr_writes(method) -> Iterable[tuple[str, ast.AST]]:
        for node in ast.walk(method):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and not CrossThreadMutation._under_lock(node)
                ):
                    yield t.attr, node

    @staticmethod
    def _under_lock(node: ast.AST) -> bool:
        for p in parents(node):
            if isinstance(p, (ast.With, ast.AsyncWith)):
                for item in p.items:
                    src = ""
                    try:
                        src = ast.unparse(item.context_expr)
                    except Exception:  # pragma: no cover - defensive
                        pass
                    if "lock" in src.lower():
                        return True
        return False


# --------------------------------------------------------------------------
# DL006 fault-site / metric registry
# --------------------------------------------------------------------------

_FIRE_ATTRS = {"fire", "fire_sync", "check"}
_METRIC_ATTRS = {"counter", "gauge", "histogram"}


class FaultSiteRegistry:
    """DL006: fault-injection sites and metric names must come from the
    committed catalog (tools/dynalint/catalog.py).

    A ``FAULTS.fire("typo.site")`` never trips — the chaos schedule that
    names the real site silently tests nothing, and a replayed
    ``DYN_FAULTS`` spec stops matching the code it was recorded against.
    Same for metric names: a renamed counter orphans every dashboard and
    alert pointing at the old name. The catalog is the reviewable,
    diffable registry; the runner also warns about *stale* entries no code
    uses any more.
    """

    id = "DL006"
    name = "fault-site-registry"

    def check(self, ctx: ScanContext) -> Iterable[Finding]:
        fault_sites = set(ctx.catalog.FAULT_SITES)
        metric_names = set(ctx.catalog.METRIC_NAMES)
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            recv = dotted(func.value) or ""
            if func.attr in _FIRE_ATTRS and "faults" in recv.lower():
                yield from self._check_site(ctx, node, fault_sites)
            elif func.attr in _METRIC_ATTRS and node.args:
                yield from self._check_metric(ctx, node, metric_names)

    def _check_site(self, ctx, node, known) -> Iterable[Finding]:
        if not node.args:
            return
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message="fault site must be a string literal (dynamic site "
                        "names can't be catalogued or replayed)",
                hint="inline the site string",
                context=qualname(node), detail="dynamic-site",
            )
            return
        site = arg.value
        ctx.used_fault_sites.add(site)
        if site not in known:
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"fault site {site!r} is not in the documented "
                        "catalog — chaos schedules naming it silently drift",
                hint="add it to tools/dynalint/catalog.py FAULT_SITES (and "
                     "runtime/faults.py KNOWN_SITES) or fix the typo",
                context=qualname(node), detail=f"site:{site}",
            )

    def _check_metric(self, ctx, node, known) -> Iterable[Finding]:
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message="metric name must be a string literal so dashboards "
                        "and the catalog can reference it",
                hint="inline the metric name",
                context=qualname(node), detail="dynamic-metric",
            )
            return
        name = arg.value
        ctx.used_metric_names.add(name)
        if name not in known:
            yield Finding(
                rule=self.id, path=ctx.path,
                line=node.lineno, col=node.col_offset,
                message=f"metric {name!r} is not registered in the catalog "
                        "— renames orphan dashboards/alerts silently",
                hint="add it to tools/dynalint/catalog.py METRIC_NAMES or "
                     "fix the typo",
                context=qualname(node), detail=f"metric:{name}",
            )


RULES = {
    r.id: r
    for r in (
        BlockingCallInAsync(),
        OrphanedTask(),
        SwallowedException(),
        ResourcePairing(),
        CrossThreadMutation(),
        FaultSiteRegistry(),
    )
}
