"""DL001 fixture: blocking calls in async / loop-reachable code."""

import asyncio
import socket
import subprocess
import threading
import time
import urllib.request

_lock = threading.Lock()


async def stalls_the_loop():
    time.sleep(0.5)  # EXPECT: DL001
    subprocess.run(["ls"])  # EXPECT: DL001
    urllib.request.urlopen("http://example.com")  # EXPECT: DL001
    socket.create_connection(("localhost", 1))  # EXPECT: DL001
    f = open("/etc/hostname")  # EXPECT: DL001
    _lock.acquire()  # EXPECT: DL001
    return f


async def alias_dodge():
    import time as _time

    _time.sleep(0.5)  # EXPECT: DL001


def sync_but_loop_reachable():
    # module imports asyncio: sync time.sleep is tier-2 flagged
    time.sleep(0.1)  # EXPECT: DL001


def proven_thread_only():
    # dynalint: disable=DL001 -- fixture: daemon-thread poll loop only
    time.sleep(0.1)


async def clean():
    await asyncio.sleep(0.5)  # asyncio.sleep is fine
    _lock.acquire(timeout=1.0)  # timed acquire is fine
    await asyncio.to_thread(time.sleep, 0.1)  # referenced, not called
    await asyncio.to_thread(lambda: time.sleep(0.1))  # lambda = off-loop

    def helper():  # nested sync def: not the coroutine's body
        subprocess.run(["ls"])  # only tier-2 time.sleep applies to sync

    return helper
