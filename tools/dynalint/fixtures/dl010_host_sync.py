"""DL010 fixture: host↔device syncs on the step-thread hot path.

``_loop`` is a ``threading.Thread`` target, so it (and everything it
calls) is hot; ``decode_step`` is jit-registered, so its results are
device-tainted. Unaccounted syncs on tainted values flag; the same sync
wrapped in the accounted-phase idiom (``self._phase("...d2h...")``) or
carrying a reasoned suppression does not, and neither does any of it on
a function no thread ever targets.
"""
import threading

import jax


def _impl(x):
    return x * 2


decode_step = jax.jit(_impl)


class Engine:
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _phase(self, name):
        ...

    def _loop(self):
        logits = decode_step(1)
        jax.device_get(logits)  # EXPECT: DL010
        val = float(logits)  # EXPECT: DL010
        with self._phase("dispatch.d2h_wait"):
            host = jax.device_get(logits)  # accounted sync: clean
        # dynalint: disable=DL010 -- deliberate warm-up barrier: runs
        # once before the loop admits traffic
        jax.block_until_ready(logits)
        return val, host

    def off_thread(self):
        # not hot: no thread targets this method
        logits = decode_step(2)
        return float(logits)
