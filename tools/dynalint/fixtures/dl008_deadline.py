"""DL008 fixture: deadline-taint — request contexts that stop flowing.

``_Engine.generate`` takes a ``context`` parameter, which (via the
project symbol table) makes every ``.generate(...)`` call site a
deadline-accepting callee: callers holding a request context must
forward it (or a ``.child()``), and ``{"kind": "req"}`` frames must ship
``context.wire_headers()``.
"""

Context = None
framing = None


class _Engine:
    async def generate(self, request, context):
        yield request


class Operator:
    def __init__(self, engine):
        self.engine = engine

    async def forwards_is_clean(self, request, context):
        async for item in self.engine.generate(request, context):
            yield item

    async def forwards_child_is_clean(self, request, context):
        sub = context.child("sub")
        async for item in self.engine.generate(request, sub):
            yield item

    async def drops_context(self, request, context):
        async for item in self.engine.generate(request):  # EXPECT: DL008
            yield item

    async def detaches_deadline(self, request, context):
        fresh = Context()  # EXPECT: DL008
        async for item in self.engine.generate(request, fresh):
            yield item

    async def suppressed_negative(self, request, context):
        # dynalint: disable=DL008 -- fixture: fire-and-forget audit probe,
        # deliberately unbounded by the caller's deadline
        async for item in self.engine.generate(request):
            yield item


async def send_req_is_clean(writer, context):
    await framing.write_frame(writer, {
        "kind": "req", "req": context.id, "payload": None,
        "headers": context.wire_headers(),
    })


async def send_req_drops_header(writer, context):
    await framing.write_frame(writer, {  # EXPECT: DL008
        "kind": "req", "req": context.id, "payload": None,
        "headers": context.headers,
    })
