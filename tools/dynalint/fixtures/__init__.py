"""Golden fixtures for the dynalint rule suite.

Each ``dl00N_*.py`` file is scanned by tests/test_static_analysis.py.
Lines carrying a ``# EXPECT: DLnnn`` comment must produce exactly that
finding (true positive); lines carrying a suppression comment must NOT
(suppressed negative); everything else must stay quiet (clean negative).
The fixtures are never imported — syntax-valid is all they need to be.
"""
