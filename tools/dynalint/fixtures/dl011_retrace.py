"""DL011 fixture: retrace hazards around the jit boundary.

Inside a jit-wrapped body, Python branching on a traced parameter's
VALUE flags; branching on its structure (``.shape``, ``len``,
``is None``, ``is_quant``) does not. At call sites, feeding a
``static_argnames`` parameter a per-call-varying expression
(``len(...)``, ``.shape``, arithmetic) flags; literals are clean.
"""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("n_steps",))
def step(tokens, n_steps):
    if tokens > 0:  # EXPECT: DL011
        return tokens + n_steps
    while tokens:  # EXPECT: DL011
        tokens = tokens - 1
    if tokens.shape[0] > 4:  # structural (.shape): clean
        return tokens * 2
    if tokens is None:  # pytree-structure check: clean
        return jnp.zeros(())
    if len(tokens) > 2:  # structural (len): clean
        return tokens
    return tokens


def caller(batch):
    a = step(batch, n_steps=4)  # literal static: clean
    b = step(batch, n_steps=len(batch))  # EXPECT: DL011
    # dynalint: disable=DL011 -- bucketed upstream: cfg.bucket_for pins
    # the value to a fixed set, so the retrace count is bounded
    c = step(batch, n_steps=batch.shape[0] + 1)
    return a, b, c
