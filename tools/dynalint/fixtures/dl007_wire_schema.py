"""DL007 fixture: cross-process wire-schema drift.

Self-contained protocol: a client half (``hub._call`` senders + one err
emitter) and a server half (a dispatch chain + one err-code handler) in
ONE file — fixture files join the "hub" channel, so sender/handler
matching works on a single-file scan exactly like the real
hub_client/hub_server pair does project-wide.
"""

hub = None


def lookup_is_clean():
    # op handled below, field read by the branch: silent
    return hub._call("lookup", key="a")


def typoed_op():
    return hub._call("lokup", key="a")  # EXPECT: DL007


def stray_field():
    return hub._call("lookup", key="a", shard=0)  # EXPECT: DL007


def suppressed_negative():
    # dynalint: disable=DL007 -- fixture: next-PR op; the server branch
    # lands together with the feature flag
    return hub._call("experimental", key="a")


def emit_known_err(req_id):
    # code mapped by handle_codes below: silent
    return {"kind": "err", "req": req_id, "code": "unavailable"}


def emit_unmapped_err(req_id):
    return {"kind": "err", "req": req_id, "code": "throttled"}  # EXPECT: DL007


def handle_codes(frame):
    code = frame.get("code")
    if code == "unavailable":
        return True
    return False


async def _dispatch(msg, send):
    op = msg.get("op")
    if op == "lookup":
        await send({"id": msg.get("id"), "ok": True, "result": msg["key"]})
        return
    if op == "evict":
        # handled-but-never-sent: surfaces as a runner WARNING on
        # project scans, never a finding
        await send({"id": msg.get("id"), "ok": True, "result": msg["key"]})
        return
    raise ValueError(f"unknown op {op!r}")
