"""DL014 fixture: capability-gated downgrades that account for nothing.

A gate built from a catalogued capability probe (use_pallas /
lane_aligned) whose fallback branch neither calls ``note_fallback`` nor
logs flags. The same shape with the downgrade counted, logged, or
suppressed with the measurement contract does not.
"""
import logging

from dynamo_tpu.ops.fallback import note_fallback

log = logging.getLogger(__name__)


def use_pallas():
    return False


def lane_aligned(d):
    return d % 128 == 0


def fast(x):
    return x


def slow(x):
    return x


def dispatch_bad(x):
    if use_pallas():  # EXPECT: DL014
        return fast(x)
    return slow(x)


def dispatch_counted(x):
    if use_pallas():
        return fast(x)
    note_fallback("no_pallas_backend", expected=True)
    return slow(x)


def dispatch_logged(x, d):
    ok = lane_aligned(d)
    if not ok:
        log.warning("lane-misaligned pool: XLA path")
        return slow(x)
    return fast(x)


def dispatch_bench(x):
    # dynalint: disable=DL014 -- bench harness: the caller records
    # which path it measured, a counter here would double-book
    if use_pallas():
        return fast(x)
    return slow(x)
