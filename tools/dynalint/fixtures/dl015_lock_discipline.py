"""DL015 fixture: threading locks across await; lock-order inversion.

A SYNC ``with <lock>:`` whose body awaits, inside ``async def``, flags
(asyncio.Lock via ``async with`` is DL009's beat — this is the
threading.Lock shape that freezes the loop). Two functions taking the
same two locks in opposite orders flag at both inner acquisition sites.
"""
import asyncio
import threading


class Pools:
    def __init__(self):
        self._alloc_lock = threading.Lock()
        self._tier_lock = threading.Lock()
        self._io_lock = threading.Lock()

    async def drain(self):
        with self._alloc_lock:  # EXPECT: DL015
            await asyncio.sleep(0.1)
        return None

    async def snapshot(self):
        # safe shape: snapshot under the lock, await after release
        with self._alloc_lock:
            n = 1
        await asyncio.sleep(0)
        return n

    async def bootstrap(self):
        # dynalint: disable=DL015 -- startup-only: runs before the loop
        # serves traffic, nothing can contend yet
        with self._io_lock:
            await asyncio.sleep(0)

    def promote(self):
        with self._alloc_lock:
            with self._tier_lock:  # EXPECT: DL015
                return 1

    def evict(self):
        with self._tier_lock:
            with self._alloc_lock:  # EXPECT: DL015
                return 2

    def stats(self):
        # consistent order (matches promote): clean
        with self._alloc_lock:
            with self._io_lock:
                return 3

    def totals(self):
        with self._alloc_lock:
            with self._io_lock:
                return 4
