"""DL003 fixture: broad exception handlers that swallow failures."""

import logging

log = logging.getLogger("fixture")


def swallows():
    try:
        risky()
    except Exception:  # EXPECT: DL003
        pass


def swallows_bare():
    try:
        risky()
    except:  # noqa: E722  # EXPECT: DL003
        return None


def swallows_in_tuple():
    try:
        risky()
    except (ValueError, Exception):  # EXPECT: DL003
        return 0


def contract_drop():
    try:
        risky()
    # dynalint: disable=DL003 -- fixture: drop-don't-block contract
    except Exception:
        pass


def logs_it():
    try:
        risky()
    except Exception:
        log.warning("risky failed", exc_info=True)


def reraises():
    try:
        risky()
    except Exception:
        raise


def uses_the_value():
    try:
        risky()
    except Exception as e:
        return {"error": str(e)}


def narrow_is_fine():
    try:
        risky()
    except ValueError:
        pass  # narrow catches are a deliberate decision, not a dragnet


def risky():
    raise ValueError("boom")
