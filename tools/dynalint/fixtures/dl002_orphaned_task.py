"""DL002 fixture: fire-and-forget tasks without a strong reference."""

import asyncio

_tasks: set = set()


async def work():
    pass


async def orphans():
    asyncio.create_task(work())  # EXPECT: DL002
    asyncio.ensure_future(work())  # EXPECT: DL002
    loop = asyncio.get_running_loop()
    loop.create_task(work())  # EXPECT: DL002
    asyncio.get_running_loop().create_task(work())  # EXPECT: DL002
    _ = asyncio.create_task(work())  # EXPECT: DL002


async def suppressed_negative():
    # dynalint: disable=DL002 -- fixture: process-lifetime task, loop
    # outlives it by construction
    asyncio.create_task(work())


class Holder:
    def __init__(self):
        self._task = None

    async def clean(self):
        # assigned to an attribute: strong reference held
        self._task = asyncio.create_task(work())
        # kept in a collection: strong reference held
        _tasks.add(asyncio.create_task(work()))
        # local kept and used
        t = asyncio.create_task(work())
        t.add_done_callback(_tasks.discard)
        # done-callback chained directly (the rule's documented out)
        asyncio.create_task(work()).add_done_callback(print)
        return t
