"""DL009 fixture: async lock spans that await wire/blocking latency.

``_dial`` reaches ``asyncio.open_connection`` so the call-graph pass
wire-taints it: awaiting it (or ``framing.write_frame`` directly) inside
an ``async with ...lock:`` span — or between an untimed
``await lock.acquire()`` and its ``release()`` — is a finding.
"""

import asyncio

framing = None


class Channel:
    def __init__(self):
        self._send_lock = asyncio.Lock()
        self._state_lock = asyncio.Lock()
        self._writer = None
        self._peers = []

    async def _dial(self):
        # wire primitive: everything that (transitively) awaits this is
        # wire-tagged
        return await asyncio.open_connection("127.0.0.1", 1)

    async def direct_wire_await(self, msg):
        async with self._send_lock:  # EXPECT: DL009
            await framing.write_frame(self._writer, msg)

    async def wire_via_helper(self):
        async with self._state_lock:  # EXPECT: DL009
            self._writer = await self._dial()

    async def pure_compute_is_clean(self, item):
        async with self._state_lock:
            self._peers.append(item)

    async def snapshot_then_send_is_clean(self, msg):
        async with self._state_lock:
            peers = list(self._peers)
        for _p in peers:
            await framing.write_frame(self._writer, msg)

    async def acquire_span(self, msg):
        await self._send_lock.acquire()  # EXPECT: DL009
        await framing.write_frame(self._writer, msg)
        self._send_lock.release()

    async def suppressed_negative(self, msg):
        # dynalint: disable=DL009 -- fixture: per-connection frame writes
        # must serialize; the span is bounded by socket backpressure
        async with self._send_lock:
            await framing.write_frame(self._writer, msg)
