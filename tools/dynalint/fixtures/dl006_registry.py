"""DL006 fixture: fault-site and metric-name catalog conformance.

Scanned with the REAL catalog (tools/dynalint/catalog.py), so the clean
cases must use real catalogued names.
"""

FAULTS = None
metrics_registry = None


def known_sites_are_clean():
    FAULTS.fire_sync("engine.step")
    return FAULTS.fire("transport.send")


def unknown_site():
    FAULTS.fire_sync("engine.setp")  # EXPECT: DL006  (typo'd site)


def dynamic_site(name):
    FAULTS.fire_sync("trans" + name)  # EXPECT: DL006


def suppressed_negative():
    # dynalint: disable=DL006 -- fixture: experimental site, catalogued
    # in the next PR
    FAULTS.fire_sync("engine.experimental")


def known_metric_is_clean():
    return metrics_registry.counter(
        "http_requests_total", "HTTP requests", ["model"]
    )


def unknown_metric():
    return metrics_registry.counter(  # EXPECT: DL006
        "http_request_total", "typo'd: orphans every dashboard", []
    )
