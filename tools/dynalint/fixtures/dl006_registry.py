"""DL006 fixture: fault-site, metric-name, and span-name catalog
conformance.

Scanned with the REAL catalog (tools/dynalint/catalog.py), so the clean
cases must use real catalogued names.
"""

FAULTS = None
metrics_registry = None
tracing = None


def known_sites_are_clean():
    FAULTS.fire_sync("engine.step")
    return FAULTS.fire("transport.send")


def unknown_site():
    FAULTS.fire_sync("engine.setp")  # EXPECT: DL006  (typo'd site)


def dynamic_site(name):
    FAULTS.fire_sync("trans" + name)  # EXPECT: DL006


def suppressed_negative():
    # dynalint: disable=DL006 -- fixture: experimental site, catalogued
    # in the next PR
    FAULTS.fire_sync("engine.experimental")


def guided_names_are_clean():
    # the guided-decoding registry additions resolve as known names in
    # all three catalogs (fault site, metric, span)
    FAULTS.fire_sync("engine.guided_compile")
    metrics_registry.counter(
        "guided_requests_total", "Guided-decoding requests.", ["outcome"]
    )
    with tracing.span("engine.guided_compile"):
        pass


def known_metric_is_clean():
    return metrics_registry.counter(
        "http_requests_total", "HTTP requests", ["model"]
    )


def unknown_metric():
    return metrics_registry.counter(  # EXPECT: DL006
        "http_request_total", "typo'd: orphans every dashboard", []
    )


def known_span_is_clean():
    with tracing.span("http.request", route="chat"):
        pass
    tracing.emit_span("worker.request", None, start_ns=0, end_ns=1)


def unknown_span():
    with tracing.span("http.requests"):  # EXPECT: DL006  (typo'd span)
        pass


def dynamic_span(name):
    with tracing.span("engine." + name):  # EXPECT: DL006
        pass


def suppressed_span_negative():
    # dynalint: disable=DL006 -- fixture: experimental span, catalogued
    # in the next PR
    with tracing.span("engine.experimental"):
        pass
