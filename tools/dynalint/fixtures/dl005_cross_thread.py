"""DL005 fixture: step-thread vs event-loop attribute rebinding."""

import threading


class RacyEngine:
    def __init__(self):
        self.counter = 0
        self.status = "idle"
        self._thread = threading.Thread(target=self._thread_loop)

    def _thread_loop(self):
        while True:
            self._step()

    def _step(self):
        self.counter += 1  # thread-side write (via _thread_loop closure)
        self.status = "stepping"

    async def generate(self):
        self.counter = 0  # EXPECT: DL005
        self.status = "generating"  # EXPECT: DL005


class MediatedEngine:
    def __init__(self):
        self.counter = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._thread_loop)

    def _thread_loop(self):
        with self._lock:
            self.counter += 1

    async def generate(self):
        with self._lock:
            self.counter = 0  # lock-mediated: clean


class SuppressedEngine:
    def __init__(self):
        self.flag = False
        self._thread = threading.Thread(target=self._thread_loop)

    def _thread_loop(self):
        self.flag = True

    async def generate(self):
        # dynalint: disable=DL005 -- fixture: bool flip, GIL-atomic and
        # tolerated by the reader
        self.flag = False


class NoThreads:
    """No Thread(target=...) anywhere: the rule stays out entirely."""

    def __init__(self):
        self.x = 0

    def poke(self):
        self.x = 1

    async def agen(self):
        self.x = 2
