"""DL012 fixture: donation misuse around the jit boundary.

Registry level: a jit whose signature takes pool-sized buffers
(k_pages/v_pages) without donating them flags at the jit definition.
Call level: reading a donated buffer after the call flags; rebinding it
from the call's result in the same statement is the safe idiom and is
clean.
"""
import jax


def _step_impl(tokens, k_pages, v_pages):
    return tokens, k_pages, v_pages


decode_steps = jax.jit(_step_impl, donate_argnums=(1, 2))  # donated: clean


def _gather_impl(k_pages, v_pages, ids):
    return k_pages, v_pages


extract = jax.jit(_gather_impl)  # EXPECT: DL012


class Runner:
    def ok(self, toks):
        # rebind-in-statement: the donated names are the targets
        toks, self.k_pages, self.v_pages = decode_steps(
            toks, self.k_pages, self.v_pages
        )
        return toks

    def bad(self, toks):
        out = decode_steps(toks, self.k_pages, self.v_pages)  # EXPECT: DL012
        stale = self.k_pages
        return out, stale

    def rollback(self, toks):
        # dynalint: disable=DL012 -- double-buffered: the donated pool
        # is the PREVIOUS generation; reading it is the rollback path
        out = decode_steps(toks, self.k_pages, self.v_pages)
        prev = self.k_pages
        return out, prev
