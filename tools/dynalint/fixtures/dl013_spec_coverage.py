"""DL013 fixture: shard_map specs that don't cover the callable.

Arity: an in_specs tuple shorter/longer than the wrapped callable's
positional params flags. Pytree leaves: a quant-capable value (one the
enclosing function probes with ``is_quant``) entering shard_map under a
bare array-only ``P(...)`` spec flags — a QuantPool's scale leaves
would have no spec at all.
"""
from jax.sharding import PartitionSpec as P

from dynamo_tpu.ops.quant import is_quant
from dynamo_tpu.ops.shard import compat_shard_map


def _kernel(q, k, v):
    return q


def run(mesh, q, k, v):
    good = compat_shard_map(
        _kernel, mesh=mesh,
        in_specs=(P("tp"), P("tp"), P("tp")), out_specs=P("tp"),
    )
    a = good(q, k, v)
    bad = compat_shard_map(  # EXPECT: DL013
        _kernel, mesh=mesh,
        in_specs=(P("tp"), P("tp")), out_specs=P("tp"),
    )
    b = bad(q, k, v)
    return a, b


def run_quant(mesh, q, k_pages, v_pages):
    if is_quant(k_pages):
        k_pages = k_pages.vals
    sm = compat_shard_map(  # EXPECT: DL013
        _kernel, mesh=mesh,
        in_specs=(P(None), P(None, "tp"), P(None, "tp")),
        out_specs=P(None),
    )
    args = (q, k_pages, v_pages)
    return sm(*args)


def run_guarded(mesh, q, k_pages, v_pages):
    if is_quant(k_pages):
        raise NotImplementedError("quant pools take the counted fallback")
    # dynalint: disable=DL013 -- the guard above rejects quant pools;
    # plain array leaves are fully covered by these specs
    sm = compat_shard_map(
        _kernel, mesh=mesh,
        in_specs=(P(None), P(None, "tp"), P(None, "tp")),
        out_specs=P(None),
    )
    return sm(q, k_pages, v_pages)
