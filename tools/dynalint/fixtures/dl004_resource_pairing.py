"""DL004 fixture: KV page acquire/release pairing."""


class SeqHolder:
    def __init__(self):
        self.pages = None


def leaks_outright(allocator):
    pages = allocator.alloc_page()  # EXPECT: DL004
    return 7  # pages neither released nor transferred


def released_on_happy_path_only(allocator, model):
    pages = allocator.take_prefix([1, 2, 3])  # EXPECT: DL004
    model.forward(pages=None)  # can raise -> pages leak
    allocator.release(pages)


def suppressed_negative(allocator):
    # dynalint: disable=DL004 -- fixture: allocator is a test double that
    # reclaims everything in its own teardown
    pages = allocator.alloc_page()
    return 7


def release_in_finally(allocator, model):
    pages = allocator.alloc_page()
    try:
        model.forward(pages)
    finally:
        allocator.release(pages)


def release_in_except(allocator, model):
    pages = allocator.take_prefix([1])
    try:
        model.forward(pages)
    except Exception:
        allocator.release(pages)
        raise
    return pages  # also escapes via return on success


def ownership_transferred(allocator):
    pages = allocator.alloc_page()
    holder = SeqHolder()
    holder.pages = pages  # stored into an attribute: transferred
    return holder


def immediate_release(allocator):
    pages = allocator.alloc_page()
    allocator.release(pages)  # nothing raise-capable in between
