"""Wire-schema extraction: the cross-process protocol as the AST sees it.

The reference Dynamo shares protocol structs between client and server, so
the Rust compiler *is* the wire-schema check. Our Python reproduction
encodes three cross-process protocols purely by convention:

  hub              ``{"id": n, "op": str, ...}`` frames between hub
                   clients (hub_client.py ``_call``/``_open_stream``,
                   hub_replica.py probe/sync frames, tests/hub_cluster.py)
                   and the hub server dispatch chains
                   (``HubServer._dispatch`` + ``_dispatch_repl``);
  worker.admin     ``{"op": str, ...}`` payloads to the worker admin
                   endpoint (engine/worker.py ``admin_handler``);
  disagg.transfer  newline-JSON ``{"op": str, ...}`` control requests on
                   the KV transfer plane (disagg/transfer.py).

This module extracts BOTH directions from the ProjectIndex — every
client-side op emission with its field names, every server-side dispatch
branch with the fields it actually reads — plus the transport error codes
(``{"kind": "err", "code": ...}`` emitted vs. the codes the client maps
back to typed exceptions). DL007 (rules.py) compares them:

  * op or field sent but unhandled  -> FAIL (the exact not_leader /
    repl.status-nonce drift class the PR 2/3 review cycles hand-caught);
  * op handled but never sent       -> warn (dead protocol surface),
    silenced per-op via ``TOOLING_OPS`` with a written reason;
  * extracted schema != committed ``wire_schema.json`` -> FAIL in both
    directions (DL006-style two-way catalog drift; never baselineable).

``wire_schema.json`` is the committed, reviewable protocol catalog;
``--emit-protocol`` renders it to docs/PROTOCOL.md for humans.

Deliberately out of scope: the SPMD replay stream (parallel/spmd.py) whose
ops are *dynamic by design* (it mirrors engine entry-point names), and the
hub WAL record format (hub_store.py ``_log``/``_apply``) which never
crosses a process boundary except via repl.sync, where it is shipped as an
opaque ``rec`` payload.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import TYPE_CHECKING, Iterable

from tools.dynalint.core import Finding, dotted, parents, qualname

if TYPE_CHECKING:  # pragma: no cover
    from tools.dynalint.core import ProjectIndex, ScanContext

SCHEMA_PATH = Path(__file__).resolve().parent / "wire_schema.json"
FIXTURE_MARKER = ("dynalint", "fixtures")

# (repo path, dispatcher qualname) -> channel. These are ANCHORS: when the
# file is in scope but the qualname is gone (refactor/rename), DL007 fails
# loudly instead of silently extracting an empty server side.
DISPATCHERS: dict[tuple[str, str], str] = {
    ("dynamo_tpu/runtime/hub_server.py", "HubServer._dispatch"): "hub",
    (
        "dynamo_tpu/runtime/hub_replica.py",
        "ReplicatedHubServer._dispatch_repl",
    ): "hub",
    (
        "dynamo_tpu/engine/worker.py",
        "launch_engine_worker.admin_handler",
    ): "worker.admin",
    ("dynamo_tpu/disagg/transfer.py", "KvTransferSource._handle"):
        "disagg.transfer",
}

# Ops a server deliberately handles with no in-tree (scanned-scope) sender:
# tests and operator tooling drive them. Keyed by "channel:op" — a reason
# written for one surface must not excuse a same-named dead op on another.
# The reason is REQUIRED — it lands in wire_schema.json and
# docs/PROTOCOL.md so the surface stays documented instead of looking dead.
TOOLING_OPS: dict[str, str] = {
    "hub:ping": "liveness probe for operators/tests; no runtime caller",
    "hub:repl.append": "push-apply tooling path; the normal record tail "
                       "rides the repl.sync stream (exercised by "
                       "tests/test_hub_replication.py fencing tests)",
    "hub:repl.promote": "manual failover lever for operators — runs a "
                        "quorum vote round, never a unilateral term "
                        "seizure; elections campaign in-process without "
                        "the RPC",
    "worker.admin:faults": "chaos tooling: live DYN_FAULTS reconfiguration "
                           "(tests/test_faults.py, "
                           "recipes/chaos/nightly.sh)",
    "worker.admin:drain": "operator-triggered drain; SIGTERM drives the "
                          "same helper in-process (tests/test_faults.py)",
    "worker.admin:cache_status": "operator/debug introspection of page "
                                 "pools (tests/test_kvbm.py)",
}

# Frame envelope fields present on every op of a channel; not part of any
# one op's schema.
ENVELOPE_FIELDS = frozenset({"op", "id"})

# The request/response stream plane (runtime/transport.py) speaks
# ``{"kind": ...}`` frames rather than ops; per-kind extraction is scoped
# to THIS path only — frame-shaped dict literals elsewhere (benches,
# tests, goldens) are fixtures, not protocol.
STREAM_FRAME_PATH = "dynamo_tpu/runtime/transport.py"

# Frame kinds a peer deliberately handles with no in-tree sender, with the
# written reason (rendered into wire_schema.json + docs/PROTOCOL.md).
LEGACY_FRAME_KINDS: dict[str, str] = {
    "req": "legacy pre-compact-id request frame (uuid stream ids, "
           "headers on every frame); still served so old clients keep "
           "working, but the client now opens streams with "
           '{"kind": "open"}',
}

# Client-call attribute names that are generic hub senders: the value is
# the positional index of the op string literal (the replica's peer-RPC
# helper takes the peer address first), and keyword args are the fields.
_OP_CALL_ATTRS: dict[str, int] = {
    "_call": 0, "_open_stream": 0, "_peer_call": 1,
}
# Calls that carry a ``{"op": ...}`` dict-literal payload to a worker
# endpoint (the admin plane rides the generate transport).
_ADMIN_CARRIERS = frozenset({"call_instance", "generate", "direct"})
# Calls that put a ``{"op": ...}`` dict-literal on the transfer plane.
_TRANSFER_CARRIERS = frozenset({"_tcp_request", "dumps"})


class _Site:
    __slots__ = ("path", "line", "col", "qualname")

    def __init__(self, path: str, node: ast.AST):
        self.path = path
        self.line = getattr(node, "lineno", 1)
        self.col = getattr(node, "col_offset", 0)
        self.qualname = qualname(node)

    @property
    def ref(self) -> str:
        return f"{self.path}:{self.qualname}"


class OpInfo:
    __slots__ = ("handlers", "handled_fields", "senders", "sent_fields")

    def __init__(self) -> None:
        self.handlers: list[_Site] = []
        self.handled_fields: set[str] = set()
        self.senders: list[_Site] = []
        self.sent_fields: dict[str, list[_Site]] = {}


class WireSchema:
    def __init__(self) -> None:
        # channel -> op -> OpInfo
        self.channels: dict[str, dict[str, OpInfo]] = {}
        self.err_emitted: dict[str, list[_Site]] = {}
        self.err_handled: dict[str, list[_Site]] = {}
        # stream plane: frame kind -> {"fields": set, "sites": [_Site]}
        self.frame_emitted: dict[str, dict] = {}
        self.frame_handled: dict[str, list[_Site]] = {}
        self.missing_anchors: list[tuple[str, str]] = []

    def op(self, channel: str, op: str) -> OpInfo:
        return self.channels.setdefault(channel, {}).setdefault(op, OpInfo())

    def to_canonical(self) -> dict:
        """Deterministic, line-number-free form: what gets committed as
        wire_schema.json and what the drift check diffs against."""
        channels: dict = {}
        for channel in sorted(self.channels):
            ops: dict = {}
            for op_name in sorted(self.channels[channel]):
                info = self.channels[channel][op_name]
                fields: dict[str, str] = {}
                for f in info.handled_fields | set(info.sent_fields):
                    sent = f in info.sent_fields
                    handled = f in info.handled_fields
                    fields[f] = (
                        "both" if sent and handled
                        else "sent-only" if sent else "handled-only"
                    )
                entry = {
                    "fields": {k: fields[k] for k in sorted(fields)},
                    "handlers": sorted({s.ref for s in info.handlers}),
                    "senders": sorted({s.ref for s in info.senders}),
                }
                note = TOOLING_OPS.get(f"{channel}:{op_name}")
                if note is not None:
                    entry["note"] = note
                ops[op_name] = entry
            channels[channel] = ops
        stream_frames: dict = {
            "emitted": {
                kind: sorted(ent["fields"])
                for kind, ent in sorted(self.frame_emitted.items())
            },
            "handled": sorted(self.frame_handled),
        }
        notes = {
            k: v for k, v in sorted(LEGACY_FRAME_KINDS.items())
            if k in self.frame_handled or k in self.frame_emitted
        }
        if notes:
            stream_frames["notes"] = notes
        return {
            "version": 1,
            "tool": "dynalint",
            "channels": channels,
            "stream_frames": stream_frames,
            "transport_err_codes": {
                "emitted": sorted(self.err_emitted),
                "handled": sorted(self.err_handled),
            },
        }


# --------------------------------------------------------------------------
# extraction
# --------------------------------------------------------------------------


def _is_fixture(path: str) -> bool:
    parts = tuple(path.split("/"))
    return all(m in parts for m in FIXTURE_MARKER)


def _str_const(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _get_call_field(node: ast.AST) -> tuple[str | None, str | None]:
    """``recv.get("f", ...)`` -> (recv dotted, "f"); else (None, None)."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and node.args
    ):
        field = _str_const(node.args[0])
        if field is not None:
            return dotted(node.func.value), field
    return None, None


def _subscript_field(node: ast.AST) -> tuple[str | None, str | None]:
    """``recv["f"]`` -> (recv dotted, "f"); else (None, None)."""
    if isinstance(node, ast.Subscript):
        field = _str_const(node.slice)
        if field is not None:
            return dotted(node.value), field
    return None, None


def _extract_dispatcher(
    schema: WireSchema, ctx: "ScanContext", fn_node: ast.AST, channel: str
) -> None:
    """One server dispatch function: find the op variable(s)/receiver(s),
    then every ``op == "lit"`` branch and the message fields each branch
    (plus the shared pre-branch code) actually reads."""
    op_vars: set[str] = set()
    msg_vars: set[str] = set()
    # dispatchers that receive a pre-split (op, msg) pair as parameters
    # (hub_replica._dispatch_repl gets them from _dispatch's routing)
    args = getattr(fn_node, "args", None)
    if args is not None:
        for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
            if a.arg == "op":
                op_vars.add("op")
            elif a.arg in ("msg", "request", "req"):
                msg_vars.add(a.arg)

    def note_op_source(value: ast.AST, target: ast.AST) -> None:
        for probe in (_get_call_field, _subscript_field):
            recv, field = probe(value)
            if recv is not None and field == "op":
                msg_vars.add(recv)
                if isinstance(target, ast.Name):
                    op_vars.add(target.id)

    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            targets = node.targets[0] if len(node.targets) == 1 else None
            if (
                isinstance(targets, ast.Tuple)
                and isinstance(node.value, ast.Tuple)
                and len(targets.elts) == len(node.value.elts)
            ):
                for t, v in zip(targets.elts, node.value.elts):
                    note_op_source(v, t)
            elif targets is not None:
                note_op_source(node.value, targets)
        elif isinstance(node, ast.Compare):
            for probe in (_get_call_field, _subscript_field):
                recv, field = probe(node.left)
                if recv is not None and field == "op":
                    msg_vars.add(recv)
    if not msg_vars:
        return

    def field_reads(tree: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(tree):
            for probe in (_get_call_field, _subscript_field):
                recv, field = probe(node)
                if recv in msg_vars and field is not None:
                    out.add(field)
            # membership probes count as reads: ``"spec" in request``
            if (
                isinstance(node, ast.Compare)
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and dotted(node.comparators[0]) in msg_vars
            ):
                f = _str_const(node.left)
                if f is not None:
                    out.add(f)
        return out

    def branch_of(compare: ast.Compare) -> ast.If | None:
        child: ast.AST = compare
        for p in parents(compare):
            if isinstance(p, ast.If) and (
                p.test is child or any(child is n for n in ast.walk(p.test))
            ):
                return p
            child = p
        return None

    # pass 1: locate every op branch
    eq_branches: list[tuple[str, ast.If | None, ast.Compare]] = []
    ne_ops: list[tuple[str, ast.Compare]] = []
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1):
            continue
        # left side: an op variable, or a direct recv.get("op") call
        recv, field = _get_call_field(node.left)
        is_op_left = (
            (isinstance(node.left, ast.Name) and node.left.id in op_vars)
            or (recv in msg_vars and field == "op")
        )
        if not is_op_left:
            continue
        op_lit = _str_const(node.comparators[0])
        if op_lit is None:
            continue
        if isinstance(node.ops[0], ast.Eq):
            eq_branches.append((op_lit, branch_of(node), node))
        elif isinstance(node.ops[0], ast.NotEq):
            ne_ops.append((op_lit, node))

    all_fields = field_reads(fn_node)
    in_branch_fields: set[str] = set()
    for _op, branch, _cmp in eq_branches:
        if branch is not None:
            for stmt in branch.body:
                in_branch_fields |= field_reads(stmt)
    shared_fields = (all_fields - in_branch_fields) - {"op"}

    for op_lit, branch, cmp_node in eq_branches:
        info = schema.op(channel, op_lit)
        info.handlers.append(_Site(ctx.path, cmp_node))
        fields = set(shared_fields)
        if branch is not None:
            for stmt in branch.body:
                fields |= field_reads(stmt)
        info.handled_fields |= fields - ENVELOPE_FIELDS
    for op_lit, cmp_node in ne_ops:
        # guard form (``if op != "pull": return``): the op's handling is
        # the rest of the function — attribute every field read to it
        info = schema.op(channel, op_lit)
        info.handlers.append(_Site(ctx.path, cmp_node))
        info.handled_fields |= all_fields - ENVELOPE_FIELDS


def _record_send(
    schema: WireSchema, ctx: "ScanContext", channel: str,
    op: str, fields: Iterable[str], node: ast.AST,
) -> None:
    info = schema.op(channel, op)
    site = _Site(ctx.path, node)
    info.senders.append(site)
    for f in fields:
        if f not in ENVELOPE_FIELDS:
            info.sent_fields.setdefault(f, []).append(site)


def _dict_op_fields(d: ast.Dict) -> tuple[str | None, list[str]]:
    op = None
    fields: list[str] = []
    for k, v in zip(d.keys, d.values):
        key = _str_const(k)
        if key is None:
            continue
        if key == "op":
            op = _str_const(v)  # dynamic op -> None -> skipped by caller
        else:
            fields.append(key)
    return op, fields


def _extract_senders(schema: WireSchema, ctx: "ScanContext") -> None:
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = dotted(func) or ""
        last = name.rsplit(".", 1)[-1]
        # hub generic senders: the string literal at the attr's op index
        # IS the op, keyword args are the fields
        if isinstance(func, ast.Attribute) and func.attr in _OP_CALL_ATTRS:
            idx = _OP_CALL_ATTRS[func.attr]
            op = (
                _str_const(node.args[idx]) if len(node.args) > idx else None
            )
            if op is not None:
                kw = [k.arg for k in node.keywords if k.arg]
                _record_send(schema, ctx, "hub", op, kw, node)
            continue
        # framed hub messages: write_frame(writer, {"id": ..., "op": ...}).
        # The "id" envelope key is the hub-protocol marker — the SPMD
        # descriptor stream also write_frames ``{"op": ...}`` dicts but
        # speaks its own (deliberately dynamic) replay protocol.
        if last == "write_frame" and len(node.args) >= 2 and isinstance(
            node.args[1], ast.Dict
        ):
            keys = {_str_const(k) for k in node.args[1].keys}
            if "id" in keys:
                op, fields = _dict_op_fields(node.args[1])
                if op is not None:
                    _record_send(schema, ctx, "hub", op, fields, node)
            continue
        # dict-literal {"op": ...} payloads riding a carrier call
        if last in _ADMIN_CARRIERS or last in _TRANSFER_CARRIERS:
            channel = (
                "worker.admin" if last in _ADMIN_CARRIERS
                else "disagg.transfer"
            )
            for arg in node.args:
                if isinstance(arg, ast.Dict):
                    op, fields = _dict_op_fields(arg)
                    if op is not None:
                        _record_send(schema, ctx, channel, op, fields, node)


def _extract_err_codes(schema: WireSchema, ctx: "ScanContext") -> None:
    # emitted: {"kind": "err", ..., "code": "lit"} dicts and
    # err.update(code="lit", ...) builders
    for node in ctx.nodes:
        if isinstance(node, ast.Dict):
            keys = {
                _str_const(k): v for k, v in zip(node.keys, node.values)
            }
            if _str_const(keys.get("kind")) == "err" and "code" in keys:
                code = _str_const(keys["code"])
                if code is not None:
                    schema.err_emitted.setdefault(code, []).append(
                        _Site(ctx.path, node)
                    )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "update"
        ):
            for kw in node.keywords:
                if kw.arg == "code":
                    code = _str_const(kw.value)
                    if code is not None:
                        schema.err_emitted.setdefault(code, []).append(
                            _Site(ctx.path, node)
                        )
    # handled: compares of a var assigned from .get("code"), or direct
    # recv.get("code") == "lit"
    code_vars: set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            recv, field = _get_call_field(node.value)
            if recv is not None and field == "code" and isinstance(
                node.targets[0], ast.Name
            ):
                code_vars.add(node.targets[0].id)
    for node in ctx.nodes:
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Eq, ast.NotEq))):
            continue
        recv, field = _get_call_field(node.left)
        is_code = (
            isinstance(node.left, ast.Name) and node.left.id in code_vars
        ) or field == "code"
        if not is_code:
            continue
        code = _str_const(node.comparators[0])
        if code is not None:
            schema.err_handled.setdefault(code, []).append(
                _Site(ctx.path, node)
            )


def _extract_stream_frames(schema: WireSchema, ctx: "ScanContext") -> None:
    """Stream-plane ``{"kind": ...}`` frames (STREAM_FRAME_PATH only).

    Emitted: every dict literal with a constant ``kind`` value, with the
    other literal keys as its fields (``ch``/``req`` ride in via the
    reply-envelope ``update()`` and are documented as envelope, not
    per-kind fields). Handled: ``== "lit"`` / ``!= "lit"`` compares of a
    kind variable (assigned from ``msg.get("kind")`` or ``msg["kind"]``)
    or of the access itself, plus ``in ("end", "err")`` membership."""
    if ctx.path != STREAM_FRAME_PATH:
        return
    kind_vars: set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Dict):
            kv = {}
            for k, v in zip(node.keys, node.values):
                key = _str_const(k)
                if key is not None:
                    kv[key] = v
            kind = _str_const(kv.get("kind"))
            if kind is not None:
                ent = schema.frame_emitted.setdefault(
                    kind, {"fields": set(), "sites": []}
                )
                ent["fields"] |= set(kv) - {"kind"}
                ent["sites"].append(_Site(ctx.path, node))
        elif (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            for probe in (_get_call_field, _subscript_field):
                _recv, field = probe(node.value)
                if field == "kind":
                    kind_vars.add(node.targets[0].id)
    for node in ctx.nodes:
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1):
            continue
        is_kind = (
            isinstance(node.left, ast.Name) and node.left.id in kind_vars
        )
        if not is_kind:
            for probe in (_get_call_field, _subscript_field):
                _recv, field = probe(node.left)
                if field == "kind":
                    is_kind = True
        if not is_kind:
            continue
        if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            lit = _str_const(node.comparators[0])
            if lit is not None:
                schema.frame_handled.setdefault(lit, []).append(
                    _Site(ctx.path, node)
                )
        elif isinstance(node.ops[0], ast.In) and isinstance(
            node.comparators[0], (ast.Tuple, ast.List, ast.Set)
        ):
            for elt in node.comparators[0].elts:
                lit = _str_const(elt)
                if lit is not None:
                    schema.frame_handled.setdefault(lit, []).append(
                        _Site(ctx.path, node)
                    )


def extract(project: "ProjectIndex") -> WireSchema:
    """Build the wire schema for one ProjectIndex (memoized on it)."""
    cached = getattr(project, "_wire_schema", None)
    if cached is not None:
        return cached
    schema = WireSchema()
    anchors_found: set[tuple[str, str]] = set()
    for ctx in project.contexts:
        for (path, qual), channel in DISPATCHERS.items():
            if ctx.path != path:
                continue
            info = project.functions.get((path, qual))
            if info is None:
                continue
            anchors_found.add((path, qual))
            _extract_dispatcher(schema, ctx, info.node, channel)
        if _is_fixture(ctx.path):
            # fixtures are self-contained: any dispatcher-shaped function
            # in them joins the "hub" channel so sender/handler matching
            # works on a single-file scan
            for info in project.functions.values():
                if info.path == ctx.path:
                    _extract_dispatcher(schema, ctx, info.node, "hub")
        _extract_senders(schema, ctx)
        _extract_err_codes(schema, ctx)
        _extract_stream_frames(schema, ctx)
    scanned_paths = {ctx.path for ctx in project.contexts}
    schema.missing_anchors = [
        (path, qual)
        for (path, qual) in sorted(DISPATCHERS)
        if path in scanned_paths and (path, qual) not in anchors_found
    ]
    project._wire_schema = schema  # type: ignore[attr-defined]
    return schema


# --------------------------------------------------------------------------
# DL007 checks
# --------------------------------------------------------------------------


def check_project(project: "ProjectIndex") -> Iterable[Finding]:
    """The failing direction of DL007: sent-but-unhandled ops/fields,
    emitted-but-unmapped err codes, and missing dispatcher anchors."""
    schema = extract(project)
    for path, qual in schema.missing_anchors:
        yield Finding(
            rule="DL007", path=path, line=1, col=0,
            message=f"wire dispatcher anchor {qual!r} not found — the "
                    "schema extractor has lost the server side of this "
                    "protocol",
            hint="update tools/dynalint/wire.py DISPATCHERS for the "
                 "refactor (and re-run --update-wire-schema)",
            context=qual, detail=f"anchor:{path}:{qual}",
        )
    for channel, ops in sorted(schema.channels.items()):
        has_handlers = any(info.handlers for info in ops.values())
        if not has_handlers:
            # the channel's server side is out of scan scope (partial
            # scan): sent-op matching would be pure noise
            continue
        for op_name, info in sorted(ops.items()):
            if info.senders and not info.handlers:
                for site in info.senders:
                    yield Finding(
                        rule="DL007", path=site.path, line=site.line,
                        col=site.col,
                        message=f"op {op_name!r} is sent on the {channel} "
                                "channel but no dispatch branch handles it "
                                "— the peer answers 'unknown op'",
                        hint="fix the op name, or add the server branch "
                             "(then --update-wire-schema)",
                        context=site.qualname,
                        detail=f"op:{channel}:{op_name}",
                    )
                continue
            if not info.handlers:
                continue
            for field, sites in sorted(info.sent_fields.items()):
                if field in info.handled_fields:
                    continue
                for site in sites:
                    yield Finding(
                        rule="DL007", path=site.path, line=site.line,
                        col=site.col,
                        message=f"field {field!r} of op {op_name!r} "
                                f"({channel}) is sent but the handler "
                                "never reads it — stray payload or a "
                                "renamed server-side field",
                        hint="drop the field, or read it in the dispatch "
                             "branch (then --update-wire-schema)",
                        context=site.qualname,
                        detail=f"field:{channel}:{op_name}:{field}",
                    )
    if schema.err_handled:
        for code, sites in sorted(schema.err_emitted.items()):
            if code in schema.err_handled:
                continue
            for site in sites:
                yield Finding(
                    rule="DL007", path=site.path, line=site.line,
                    col=site.col,
                    message=f"transport err code {code!r} is emitted but "
                            "no client maps it — the peer degrades it to "
                            "a generic RuntimeError",
                    hint="map the code in the transport client (typed "
                         "exception) or reuse an existing code",
                    context=site.qualname, detail=f"errcode:{code}",
                )
    if schema.frame_handled:
        for kind, ent in sorted(schema.frame_emitted.items()):
            if kind in schema.frame_handled:
                continue
            for site in ent["sites"]:
                yield Finding(
                    rule="DL007", path=site.path, line=site.line,
                    col=site.col,
                    message=f"stream frame kind {kind!r} is emitted but "
                            "no rx path dispatches it — the peer drops "
                            "the frame on the floor",
                    hint="handle the kind in the rx dispatch, or fix the "
                         "kind string (then --update-wire-schema)",
                    context=site.qualname, detail=f"framekind:{kind}",
                )


def unsent_op_warnings(project: "ProjectIndex") -> list[str]:
    """The warn direction: server surface nothing in scope exercises."""
    schema = extract(project)
    out: list[str] = []
    for channel, ops in sorted(schema.channels.items()):
        if not any(info.senders for info in ops.values()):
            continue  # client side out of scan scope: skip the direction
        for op_name, info in sorted(ops.items()):
            if info.handlers and not info.senders and (
                f"{channel}:{op_name}" not in TOOLING_OPS
            ):
                site = info.handlers[0]
                out.append(
                    f"wire: op {op_name!r} ({channel}) is handled at "
                    f"{site.path}:{site.line} but nothing in scope sends "
                    "it — dead surface? (annotate TOOLING_OPS in "
                    "tools/dynalint/wire.py with a reason if deliberate)"
                )
    for code in sorted(set(schema.err_handled) - set(schema.err_emitted)):
        if schema.err_emitted:
            site = schema.err_handled[code][0]
            out.append(
                f"wire: transport err code {code!r} is handled at "
                f"{site.path}:{site.line} but never emitted — stale "
                "client mapping?"
            )
    for kind in sorted(set(schema.frame_handled) - set(schema.frame_emitted)):
        if schema.frame_emitted and kind not in LEGACY_FRAME_KINDS:
            site = schema.frame_handled[kind][0]
            out.append(
                f"wire: stream frame kind {kind!r} is handled at "
                f"{site.path}:{site.line} but never emitted — dead rx "
                "branch? (annotate LEGACY_FRAME_KINDS in "
                "tools/dynalint/wire.py with a reason if deliberate)"
            )
    return out


def schema_drift_findings(
    project: "ProjectIndex", schema_path: Path
) -> list[Finding]:
    """Committed-catalog drift, both directions, as DL007 findings."""
    extracted = extract(project).to_canonical()
    rel = "tools/dynalint/wire_schema.json"
    if not schema_path.exists():
        return [Finding(
            rule="DL007", path=rel, line=1, col=0,
            message="wire_schema.json is missing — the protocol catalog "
                    "must be committed",
            hint="python -m tools.dynalint --update-wire-schema",
            context="<catalog>", detail="schema-missing",
        )]
    try:
        committed = json.loads(schema_path.read_text())
    except json.JSONDecodeError as e:
        return [Finding(
            rule="DL007", path=rel, line=1, col=0,
            message=f"wire_schema.json is not valid JSON: {e}",
            hint="python -m tools.dynalint --update-wire-schema",
            context="<catalog>", detail="schema-corrupt",
        )]
    out: list[Finding] = []
    for key, msg in _diff_schema(committed, extracted):
        out.append(Finding(
            rule="DL007", path=rel, line=1, col=0,
            message=f"protocol catalog drift: {msg}",
            hint="review the protocol change, then "
                 "python -m tools.dynalint --update-wire-schema "
                 "--emit-protocol",
            context="<catalog>", detail=f"drift:{key}",
        ))
    return out


def _diff_schema(committed: dict, extracted: dict) -> list[tuple[str, str]]:
    """Both-direction diff keyed for stable fingerprints."""
    out: list[tuple[str, str]] = []
    c_ch = committed.get("channels", {})
    e_ch = extracted.get("channels", {})
    for ch in sorted(set(c_ch) | set(e_ch)):
        c_ops = c_ch.get(ch, {})
        e_ops = e_ch.get(ch, {})
        for op in sorted(set(c_ops) - set(e_ops)):
            out.append((f"{ch}:{op}:gone",
                        f"op {op!r} ({ch}) is catalogued but no longer "
                        "extracted from the code"))
        for op in sorted(set(e_ops) - set(c_ops)):
            out.append((f"{ch}:{op}:new",
                        f"op {op!r} ({ch}) exists in code but not in the "
                        "committed catalog"))
        for op in sorted(set(c_ops) & set(e_ops)):
            if c_ops[op] != e_ops[op]:
                c_f, e_f = c_ops[op].get("fields", {}), e_ops[op].get(
                    "fields", {})
                if c_f != e_f:
                    delta = sorted(
                        set(c_f.items()) ^ set(e_f.items())
                    )
                    out.append((f"{ch}:{op}:fields",
                                f"op {op!r} ({ch}) field set changed: "
                                f"{delta}"))
                else:
                    out.append((f"{ch}:{op}:sites",
                                f"op {op!r} ({ch}) sender/handler sites "
                                "changed"))
    c_sf = committed.get("stream_frames", {})
    e_sf = extracted.get("stream_frames", {})
    if c_sf != e_sf:
        out.append(("streamframes",
                    f"stream frame kinds changed: committed {c_sf}, "
                    f"extracted {e_sf}"))
    c_err = committed.get("transport_err_codes", {})
    e_err = extracted.get("transport_err_codes", {})
    if c_err != e_err:
        out.append(("errcodes",
                    f"transport err codes changed: committed {c_err}, "
                    f"extracted {e_err}"))
    return out


def save_schema(project: "ProjectIndex", schema_path: Path) -> dict:
    canonical = extract(project).to_canonical()
    schema_path.write_text(json.dumps(canonical, indent=2) + "\n")
    return canonical


# --------------------------------------------------------------------------
# docs/PROTOCOL.md renderer
# --------------------------------------------------------------------------

_CHANNEL_BLURB = {
    "hub": "Framed msgpack RPC between hub clients and the hub server "
           "(`{\"id\": n, \"op\": str, ...}` -> "
           "`{\"id\": n, \"ok\": bool, \"result\"/\"error\": ...}`; "
           "streaming ops emit `{\"id\": n, \"stream\": item}` frames). "
           "Includes the `repl.*` replication RPCs.",
    "worker.admin": "Control-plane payloads to each worker's `admin` "
                    "endpoint, riding the normal request transport "
                    "(`{\"op\": str, ...}` -> one `{\"ok\": bool, ...}` "
                    "item).",
    "disagg.transfer": "Newline-delimited JSON control requests on the KV "
                       "transfer plane's TCP socket "
                       "(`{\"op\": str, \"transfer_id\": ...}`).",
}


def render_protocol_md(canonical: dict) -> str:
    lines = [
        "# dynamo-tpu cross-process protocol catalog",
        "",
        "<!-- GENERATED by `python -m tools.dynalint --emit-protocol` from",
        "     tools/dynalint/wire_schema.json — do not hand-edit. A tier-1",
        "     test (tests/test_static_analysis.py) fails when this file",
        "     drifts from the schema the code actually implements. -->",
        "",
        "Extracted mechanically from the code by dynalint's wire-schema "
        "pass (DL007):",
        "every client-side op emission and every server-side dispatch "
        "branch, compared",
        "in both directions. `both` = the field is sent and read; "
        "`handled-only` = the",
        "server reads it but no in-scope caller sends it (optional/"
        "tooling field).",
        "",
    ]
    for channel in sorted(canonical.get("channels", {})):
        ops = canonical["channels"][channel]
        lines.append(f"## Channel `{channel}`")
        lines.append("")
        blurb = _CHANNEL_BLURB.get(channel)
        if blurb:
            lines.append(blurb)
            lines.append("")
        lines.append("| op | fields | handler | senders | note |")
        lines.append("|----|--------|---------|---------|------|")
        for op in sorted(ops):
            e = ops[op]
            fields = "<br>".join(
                f"`{f}` ({status})" for f, status in e["fields"].items()
            ) or "—"
            handlers = "<br>".join(f"`{h}`" for h in e["handlers"]) or "—"
            senders = "<br>".join(f"`{s}`" for s in e["senders"]) or (
                "— (see note)" if e.get("note") else "—"
            )
            lines.append(
                f"| `{op}` | {fields} | {handlers} | {senders} | "
                f"{e.get('note', '')} |"
            )
        lines.append("")
    sf = canonical.get("stream_frames", {})
    if sf:
        lines.append("## Stream frames (request/response data plane)")
        lines.append("")
        lines.append(
            "Length-prefixed msgpack frames on the worker transport "
            "(runtime/transport.py). Every frame after `open` carries the "
            "compact integer stream id `ch` (legacy `req` streams echo "
            "the uuid `req` instead) — that reply envelope is stamped on "
            "send and is not listed per kind."
        )
        lines.append("")
        lines.append("| kind | fields | emitted | handled | note |")
        lines.append("|------|--------|---------|---------|------|")
        emitted = sf.get("emitted", {})
        handled = set(sf.get("handled", []))
        notes = sf.get("notes", {})
        for kind in sorted(set(emitted) | handled):
            fields = ", ".join(
                f"`{f}`" for f in emitted.get(kind, [])
            ) or "—"
            lines.append(
                f"| `{kind}` | {fields} | "
                f"{'yes' if kind in emitted else 'no'} | "
                f"{'yes' if kind in handled else 'no'} | "
                f"{notes.get(kind, '')} |"
            )
        lines.append("")
    err = canonical.get("transport_err_codes", {})
    lines.append("## Transport error codes")
    lines.append("")
    lines.append(
        "`{\"kind\": \"err\", \"code\": ...}` frames on the request/"
        "response transport; the client maps each code to a typed "
        "exception (runtime/transport.py)."
    )
    lines.append("")
    lines.append("| code | emitted | handled |")
    lines.append("|------|---------|---------|")
    for code in sorted(set(err.get("emitted", [])) | set(
        err.get("handled", [])
    )):
        lines.append(
            f"| `{code}` | {'yes' if code in err.get('emitted', []) else 'no'}"
            f" | {'yes' if code in err.get('handled', []) else 'no'} |"
        )
    lines.append("")
    return "\n".join(lines)
