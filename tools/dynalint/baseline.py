"""Baseline handling: grandfathered findings that don't fail the gate.

The baseline is a committed JSON file keyed by line-number-independent
fingerprints (rule | path | enclosing qualname | rule-specific detail), so
unrelated edits to a file don't churn it. New findings always fail; stale
entries (fingerprints no current finding produces) are reported so the
baseline shrinks monotonically — ``--update-baseline`` rewrites it.

Policy (enforced by tests/test_static_analysis.py): DL001, DL002, and
DL007 may NOT be baselined — blocking-in-async and orphaned tasks are
fixed outright, and a wire-schema drift that's "grandfathered" is a
protocol break shipped to production, so DL007 fails immediately too.
"""

from __future__ import annotations

import json
from pathlib import Path

from tools.dynalint.core import Finding

NEVER_BASELINE = ("DL001", "DL002", "DL007")


def load(path: Path) -> dict[str, dict]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save(path: Path, findings: list[Finding]) -> None:
    entries = [
        {
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "context": f.context,
            "detail": f.detail,
            "message": f.message,
        }
        for f in findings
        if f.rule not in NEVER_BASELINE
    ]
    entries.sort(key=lambda e: (e["path"], e["rule"], e["fingerprint"]))
    path.write_text(json.dumps(
        {"version": 1, "tool": "dynalint", "findings": entries}, indent=2
    ) + "\n")


def split(
    findings: list[Finding], baseline: dict[str, dict]
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """(new, grandfathered, stale-baseline-entries)."""
    seen: set[str] = set()
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        fp = f.fingerprint
        seen.add(fp)
        if fp in baseline and f.rule not in NEVER_BASELINE:
            old.append(f)
        else:
            new.append(f)
    stale = [e for fp, e in baseline.items() if fp not in seen]
    return new, old, stale
