"""dynalint — project-specific static analysis for dynamo-tpu.

The upstream reference framework leans on Rust's type system plus CodeQL /
cargo-deny in CI; this Python reproduction has neither, and an entire class
of its historical bugs (GC'd fire-and-forget drain task, exported-KV-page
leaks on error paths, event-loop-blocking sleeps — all hand-fixed in PR 3)
are *mechanically detectable*. dynalint turns those reviewer-enforced
invariants into a machine-checked tier-1 gate.

Rules (see tools/dynalint/README.md for the full catalog):

    DL001  blocking-call-in-async      event-loop stalls (TTFT tail spikes)
    DL002  orphaned-task               GC'd fire-and-forget asyncio tasks
    DL003  swallowed-exception         broad except that hides failures
    DL004  resource-pairing            KV page alloc without release on all paths
    DL005  cross-thread-mutation       step-thread vs event-loop attr races
    DL006  fault-site/metric-registry  chaos-schedule + metrics name drift
    DL007  wire-schema-drift           cross-process op/field protocol drift
    DL008  deadline-taint              request deadline dropped mid-path
    DL009  lock-across-await           async lock held across wire latency

Suppression: ``dynalint: disable=<RULE> -- <reason>`` in a comment on the
offending line (or on a comment-only line directly above it); file-wide
via ``dynalint: disable-file=<RULE> -- <reason>``. (Spelled with the
placeholders here so this docstring doesn't register as a real
suppression — dynalint scans its own source.)

Run: ``python -m tools.dynalint [paths...]`` (defaults to ``dynamo_tpu``
+ ``tools`` + ``tests/hub_cluster.py``, compared against the committed
baseline ``tools/dynalint/baseline.json``; new findings always fail).
"""

from tools.dynalint.core import Finding, run_paths, scan_file  # noqa: F401
from tools.dynalint.rules import RULES  # noqa: F401

__version__ = "0.1.0"
