"""dynarace CLI: the nightly race gate.

``python -m tools.dynarace`` runs, in order:

1. **Race detection** — the concurrency test subset once under
   ``DYN_RACE=1``: every process (the pytest process AND the hub/sim
   subprocesses it spawns) dumps a vector-clock race report into a
   scratch directory; reports aggregate, dedup by fingerprint, and gate
   against the committed baseline (tools/dynarace/baseline.json —
   policy: EMPTY; suppressions with written HB justifications live in
   suppressions.py, not here).
2. **Seeded schedule sweep** (``--sweep N``) — the sweep subset once
   per seed with ``DYN_RACE_SCHED=<seed>`` also set, so order-dependent
   bugs surface on a NAMED seed. A red seed is replayed with exactly
   ``DYN_RACE=1 DYN_RACE_SCHED=<seed> pytest <test>``.

Exit code 0 = no test failure, no unsuppressed/unbaselined race across
every run. ``--sarif-out`` additionally writes a SARIF 2.1.0 artifact
via the shared tools/_sarif.py emitter (the same shape dynalint
uploads).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

from tools.dynarace import registry

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# the concurrency tier the detector rides on: hub replication smoke,
# overload acceptance (step thread vs admission vs preemption), fault
# injection, cluster-sim smoke
DETECT_TESTS = [
    "tests/test_hub_replication.py::test_election_smoke",
    "tests/test_hub_replication.py::test_replication_smoke",
    "tests/test_overload.py::test_mixed_tenant_overload_acceptance",
    "tests/test_overload.py::"
    "test_preempted_stream_onboards_from_host_tier_after_g1_evict",
    "tests/test_faults.py",
    "tests/test_cluster_sim.py::test_sim_smoke_partition_and_churn",
]
# the per-seed sweep subset: kept tight so an 8-seed sweep stays
# affordable — election/commit ordering + the engine admission/
# preemption path are where seeded reordering has caught bugs
SWEEP_TESTS = [
    "tests/test_hub_replication.py::test_election_smoke",
    "tests/test_overload.py::test_mixed_tenant_overload_acceptance",
]

RULE_DOCS = {
    "DR001": ("write-write-race",
              "two writes to a catalogued shared state with no "
              "happens-before edge between them"),
    "DR002": ("write-read-race",
              "a read of a catalogued shared state unordered with the "
              "last write"),
    "DR003": ("read-write-race",
              "a write to a catalogued shared state unordered with a "
              "prior read"),
}


def _race_key(race: dict) -> str:
    return race["fingerprint"]


def _race_site(race: dict, side: str) -> tuple[str, int]:
    """(repo-relative-ish path, line) of one side's innermost frame."""
    stack = race.get(side, {}).get("stack") or ["<unknown>:0 in ?"]
    head = stack[0]
    path, _, rest = head.partition(":")
    try:
        line = int(rest.split(" ", 1)[0])
    except ValueError:
        line = 1
    return path, line


def run_pytest(
    tests: list[str],
    report_dir: str,
    seed: str | None,
    timeout: float,
    extra_env: dict[str, str] | None = None,
) -> int:
    env = dict(os.environ)
    env["DYN_RACE"] = "1"
    env["DYN_RACE_REPORT"] = report_dir
    env.pop("DYN_RACE_SCHED", None)
    if seed is not None:
        env["DYN_RACE_SCHED"] = seed
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         *tests],
        cwd=REPO_ROOT, env=env, timeout=timeout,
    )
    return proc.returncode


def collect_reports(report_dir: str) -> tuple[list[dict], list[dict], int]:
    """(unsuppressed races, suppressed races, ops) aggregated over every
    per-process report in the directory, fingerprint-deduped."""
    races: dict[str, dict] = {}
    suppressed: dict[str, dict] = {}
    ops = 0
    for path in sorted(glob.glob(os.path.join(report_dir, "race_*.json"))):
        try:
            doc = json.loads(Path(path).read_text())
        except (OSError, json.JSONDecodeError):
            continue
        ops += int(doc.get("ops", 0))
        for r in doc.get("races", []):
            races.setdefault(_race_key(r), r)
        for r in doc.get("suppressed", []):
            suppressed.setdefault(_race_key(r), r)
    return list(races.values()), list(suppressed.values()), ops


def render_text(race: dict) -> str:
    lines = [
        f"{race['rule']} race on {race['state']!r} "
        f"[{race['fingerprint']}]",
        f"  prior   ({race['prior'].get('thread', '?')}):",
        *(f"    {fr}" for fr in race["prior"].get("stack", [])),
        f"  current ({race['current'].get('thread', '?')}):",
        *(f"    {fr}" for fr in race["current"].get("stack", [])),
    ]
    return "\n".join(lines)


def render_sarif(races: list[dict]) -> str:
    from tools import _sarif

    rules = [
        _sarif.SarifRule(id=rid, name=name, short=doc, full=doc)
        for rid, (name, doc) in sorted(RULE_DOCS.items())
    ]
    results = []
    for r in races:
        uri, line = _race_site(r, "current")
        p_uri, p_line = _race_site(r, "prior")
        state = r["state"]
        desc = registry.SHARED_STATE.get(state, "")
        results.append(_sarif.SarifResult(
            rule_id=r["rule"],
            message=(
                f"data race on {state!r}: this access has no "
                f"happens-before edge to the conflicting access on "
                f"thread {r['prior'].get('thread', '?')!r}. {desc}"
            ),
            uri=uri, line=line, col=1,
            fingerprint=r["fingerprint"],
            related=[(p_uri, p_line,
                      f"conflicting access "
                      f"({r['prior'].get('thread', '?')})")],
        ))
    return _sarif.render(
        "dynarace",
        "https://example.invalid/dynamo-tpu/tools/dynarace",
        rules, results, "dynaraceFingerprint/v1",
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynarace",
        description="Happens-before race gate for dynamo-tpu.",
    )
    ap.add_argument("tests", nargs="*", default=None,
                    help="pytest node ids for the detect pass "
                         "(default: the concurrency tier)")
    ap.add_argument("--sweep", type=int, default=0, metavar="N",
                    help="additionally run the sweep subset under N "
                         "schedule seeds (seed-base..seed-base+N-1)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--sweep-tests", nargs="*", default=None,
                    help="pytest node ids for the per-seed sweep "
                         "(default: election smoke + overload "
                         "acceptance)")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE))
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--sarif-out", default=None, metavar="PATH",
                    help="also write a SARIF 2.1.0 artifact of the "
                         "unsuppressed races")
    ap.add_argument("--show-suppressed", action="store_true")
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get("DYN_RACE_TIMEOUT",
                                                 "1800")),
                    help="per-pytest-run timeout (seconds)")
    args = ap.parse_args(argv)

    detect_tests = args.tests or DETECT_TESTS
    sweep_tests = (args.sweep_tests if args.sweep_tests is not None
                   else SWEEP_TESTS)

    t0 = time.monotonic()
    rc = 0
    all_races: dict[str, dict] = {}
    all_suppressed: dict[str, dict] = {}
    total_ops = 0

    with tempfile.TemporaryDirectory(prefix="dynarace_") as tmp:
        print(f"dynarace: detect pass over {len(detect_tests)} "
              f"node(s)", file=sys.stderr)
        detect_dir = os.path.join(tmp, "detect")
        test_rc = run_pytest(detect_tests, detect_dir, None, args.timeout)
        if test_rc != 0:
            print(f"dynarace: detect-pass pytest failed (rc={test_rc})",
                  file=sys.stderr)
            rc = 1
        races, suppressed, ops = collect_reports(detect_dir)
        for r in races:
            all_races.setdefault(_race_key(r), r)
        for r in suppressed:
            all_suppressed.setdefault(_race_key(r), r)
        total_ops += ops

        for i in range(args.sweep):
            seed = str(args.seed_base + i)
            print(f"dynarace: schedule sweep seed={seed}",
                  file=sys.stderr)
            seed_dir = os.path.join(tmp, f"seed_{seed}")
            test_rc = run_pytest(
                sweep_tests, seed_dir, seed, args.timeout
            )
            if test_rc != 0:
                print(
                    f"dynarace: seed {seed} FAILED — replay with "
                    f"DYN_RACE=1 DYN_RACE_SCHED={seed} python -m "
                    f"pytest {' '.join(sweep_tests)}",
                    file=sys.stderr,
                )
                rc = 1
            races, suppressed, ops = collect_reports(seed_dir)
            for r in races:
                all_races.setdefault(_race_key(r), r)
            for r in suppressed:
                all_suppressed.setdefault(_race_key(r), r)
            total_ops += ops

    baseline_fps: set[str] = set()
    if not args.no_baseline:
        try:
            doc = json.loads(Path(args.baseline).read_text())
            baseline_fps = {e["fingerprint"]
                            for e in doc.get("findings", [])}
        except (OSError, json.JSONDecodeError):
            pass
    new = [r for fp, r in sorted(all_races.items())
           if fp not in baseline_fps]

    for r in new:
        print(render_text(r))
    if args.show_suppressed:
        for r in sorted(all_suppressed.values(),
                        key=lambda x: x["fingerprint"]):
            print(f"[suppressed: {r.get('suppressed_reason', '')[:80]}]")
            print(render_text(r))
    if args.sarif_out:
        Path(args.sarif_out).write_text(render_sarif(new))
        print(f"dynarace: SARIF artifact -> {args.sarif_out}",
              file=sys.stderr)

    dt = time.monotonic() - t0
    print(
        f"dynarace: {len(new)} unsuppressed race(s), "
        f"{len(all_races) - len(new)} baselined, "
        f"{len(all_suppressed)} suppressed over {total_ops} "
        f"instrumented ops in {dt:.1f}s",
        file=sys.stderr,
    )
    if new:
        rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
