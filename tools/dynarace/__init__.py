"""dynarace: happens-before race detector + deterministic schedule
explorer for the repo's step-thread/event-loop concurrency model.

Two layers, both keyed to the same instrumentation vocabulary
(``dynamo_tpu/runtime/race.py`` shim, no-op unless ``DYN_RACE=1``):

1. **Vector-clock happens-before detection** (detector.py): every
   instrumented lock/queue/event/thread operation maintains vector
   clocks; every ``race.read/write`` on a catalogued shared state
   (registry.py) is checked against the last conflicting access — a
   write racing a read/write with no happens-before edge is reported
   with both stack pairs and a line-independent fingerprint, gated
   through the same baseline/suppression discipline as dynalint.

2. **Seeded deterministic schedule exploration** (sched.py,
   ``DYN_RACE_SCHED=<seed>``): replayable yield points at instrumented
   boundaries, biased toward just-released locks and just-put queue
   items (loom/rr-style), so order-dependent bugs surface on a named
   seed instead of once-per-thousand chaos runs. The yield-point trace
   is a pure function of (seed, site, kind, occurrence index): the same
   seed replays the same perturbation.

Entry points: ``python -m tools.dynarace`` (the nightly gate: race
detection + N-seed schedule sweep over the concurrency test subset),
and in-process via ``tools.dynarace.runtime`` for regression tests.
"""
