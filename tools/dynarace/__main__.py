import sys

from tools.dynarace.cli import main

sys.exit(main())
