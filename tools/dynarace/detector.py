"""Vector-clock happens-before race detector (FastTrack-lite).

Model
-----
Each thread carries a vector clock ``C_t: tid -> int``. Each sync token
(lock, queue, event, thread object, ad-hoc hand-off) carries a clock
``L``. The instrumented operations maintain:

- ``release(token)``: ``L := L ⊔ C_t``; then ``C_t[t] += 1`` (the
  releasing thread's subsequent work is NOT ordered before the release).
- ``acquire(token)``: ``C_t := C_t ⊔ L``.
- ``fork(thread)``: release on the thread object; the child's first
  instrumented operation acquires from it (detected lazily via
  ``threading.current_thread()``).
- ``join(thread)``: the parent acquires the child's final clock.

Shared state uses last-access epochs: an access by thread ``t`` at
clock value ``k = C_t[t]`` happens-before a later access by ``u`` iff
``C_u[t] >= k``. Per catalogued state we keep the last write epoch and
a read map; on each access the conflicting prior epochs are checked and
violations recorded as races:

- DR001 write-write  (two unordered writes)
- DR002 write-read   (a read unordered with the last write)
- DR003 read-write   (a write unordered with a prior read)

Reports carry both sides' short stacks, the thread names, and a
line-independent fingerprint ``sha1(rule|state|siteA|siteB)`` with
sites normalized to ``path::function`` — DL005-style, so baselines and
suppressions survive rebases.

Everything is guarded by one internal (uninstrumented) lock; the
detector never calls back into instrumented code.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Any

from tools.dynarace.suppressions import SUPPRESSED_STATES

# frames from these path fragments are instrumentation, not evidence
_SKIP_FRAGMENTS = ("tools/dynarace/", "dynamo_tpu/runtime/race.py")
_STACK_DEPTH = 5


def _site_stack() -> list[str]:
    """Short stack of the instrumented call: up to _STACK_DEPTH frames
    of ``path:line in func``, innermost first, skipping dynarace's own
    frames. Cheap enough to capture at every catalogued access (this
    only ever runs under DYN_RACE=1)."""
    out: list[str] = []
    f = sys._getframe(1)
    while f is not None and len(out) < _STACK_DEPTH:
        fn = f.f_code.co_filename.replace(os.sep, "/")
        if not any(s in fn for s in _SKIP_FRAGMENTS):
            short = "/".join(fn.rsplit("/", 3)[-3:])
            out.append(f"{short}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
    return out


def _norm_site(stack: list[str]) -> str:
    """Line-independent anchor of a stack: ``path::func`` of the
    innermost non-instrumentation frame."""
    if not stack:
        return "<unknown>"
    head = stack[0]
    path, _, rest = head.partition(":")
    func = rest.partition(" in ")[2]
    return f"{path}::{func}"


@dataclass
class Access:
    """One remembered shared-state access epoch."""

    tid: int
    clock: int  # the accessor's own component at access time
    thread_name: str
    stack: list[str] = field(default_factory=list)


@dataclass
class Race:
    """One detected (or suppressed) race."""

    rule: str  # DR001 | DR002 | DR003
    state: str
    prior: Access
    current: Access
    suppressed_reason: str | None = None

    @property
    def fingerprint(self) -> str:
        a = _norm_site(self.prior.stack)
        b = _norm_site(self.current.stack)
        lo, hi = sorted((a, b))
        raw = f"{self.rule}|{self.state}|{lo}|{hi}"
        return hashlib.sha1(raw.encode()).hexdigest()[:12]

    def to_dict(self) -> dict[str, Any]:
        return {
            "rule": self.rule,
            "state": self.state,
            "fingerprint": self.fingerprint,
            "suppressed_reason": self.suppressed_reason,
            "prior": {
                "thread": self.prior.thread_name,
                "stack": self.prior.stack,
            },
            "current": {
                "thread": self.current.thread_name,
                "stack": self.current.stack,
            },
        }

    def render(self) -> str:
        kind = {
            "DR001": "write/write",
            "DR002": "write/read",
            "DR003": "read/write",
        }[self.rule]
        lines = [
            f"{self.rule} {kind} race on {self.state!r} "
            f"[{self.fingerprint}]",
            f"  prior   ({self.prior.thread_name}):",
            *(f"    {fr}" for fr in self.prior.stack),
            f"  current ({self.current.thread_name}):",
            *(f"    {fr}" for fr in self.current.stack),
        ]
        return "\n".join(lines)


class _Var:
    __slots__ = ("last_write", "reads")

    def __init__(self) -> None:
        self.last_write: Access | None = None
        self.reads: dict[int, Access] = {}


class Detector:
    """Process-wide happens-before state. One instance per process
    (module singleton in tools/dynarace/runtime.py)."""

    def __init__(self) -> None:
        # plain threading.Lock: never instrumented, never calls out
        self._lock = threading.Lock()
        self._clocks: dict[int, dict[int, int]] = {}  # tid -> VC
        self._tokens: dict[int, dict[int, int]] = {}  # id(obj) -> VC
        # strong refs so id() keys can't be reused under us
        self._token_refs: dict[int, Any] = {}
        self._vars: dict[str, _Var] = {}
        self._races: list[Race] = []
        self._seen_fps: set[str] = set()
        self.ops = 0  # instrumented-operation counter (stats)

    # -- clock plumbing ---------------------------------------------------

    def _clock(self, tid: int) -> dict[int, int]:
        c = self._clocks.get(tid)
        if c is None:
            c = {tid: 1}
            # fork edge: a brand-new thread inherits the clock its
            # parent released onto the Thread object before .start()
            tok = self._tokens.get(id(threading.current_thread()))
            if tok is not None:
                for t, k in tok.items():
                    if c.get(t, 0) < k:
                        c[t] = k
            self._clocks[tid] = c
        return c

    @staticmethod
    def _merge(dst: dict[int, int], src: dict[int, int]) -> None:
        for t, k in src.items():
            if dst.get(t, 0) < k:
                dst[t] = k

    # -- sync operations --------------------------------------------------

    def acquire(self, token: Any, site: str = "") -> None:
        tid = threading.get_ident()
        with self._lock:
            self.ops += 1
            c = self._clock(tid)
            tok = self._tokens.get(id(token))
            if tok is not None:
                self._merge(c, tok)

    def release(self, token: Any, site: str = "") -> None:
        tid = threading.get_ident()
        with self._lock:
            self.ops += 1
            c = self._clock(tid)
            key = id(token)
            tok = self._tokens.get(key)
            if tok is None:
                tok = {}
                self._tokens[key] = tok
                self._token_refs[key] = token
            self._merge(tok, c)
            c[tid] = c.get(tid, 0) + 1

    def fork(self, thread: Any, site: str = "") -> None:
        self.release(thread, site)

    def join(self, thread: Any, site: str = "") -> None:
        tid = threading.get_ident()
        child = getattr(thread, "ident", None)
        with self._lock:
            self.ops += 1
            c = self._clock(tid)
            if child is not None and child in self._clocks:
                self._merge(c, self._clocks[child])

    # -- shared-state accesses --------------------------------------------

    def _record(self, rule: str, state: str, prior: Access,
                current: Access) -> None:
        race = Race(rule, state, prior, current,
                    suppressed_reason=SUPPRESSED_STATES.get(state))
        if race.fingerprint in self._seen_fps:
            return
        self._seen_fps.add(race.fingerprint)
        self._races.append(race)

    @staticmethod
    def _ordered(prior: Access, c: dict[int, int]) -> bool:
        """prior happens-before the current thread's clock ``c``?"""
        return c.get(prior.tid, 0) >= prior.clock

    def read(self, state: str) -> None:
        tid = threading.get_ident()
        stack = _site_stack()
        with self._lock:
            self.ops += 1
            c = self._clock(tid)
            me = Access(tid, c.get(tid, 0), threading.current_thread().name,
                        stack)
            var = self._vars.setdefault(state, _Var())
            w = var.last_write
            if w is not None and w.tid != tid and not self._ordered(w, c):
                self._record("DR002", state, w, me)
            var.reads[tid] = me

    def write(self, state: str) -> None:
        tid = threading.get_ident()
        stack = _site_stack()
        with self._lock:
            self.ops += 1
            c = self._clock(tid)
            me = Access(tid, c.get(tid, 0), threading.current_thread().name,
                        stack)
            var = self._vars.setdefault(state, _Var())
            w = var.last_write
            if w is not None and w.tid != tid and not self._ordered(w, c):
                self._record("DR001", state, w, me)
            for r in var.reads.values():
                if r.tid != tid and not self._ordered(r, c):
                    self._record("DR003", state, r, me)
            var.last_write = me
            # a write ordered after the reads subsumes them; racing reads
            # were already recorded above
            var.reads = {}

    # -- reporting --------------------------------------------------------

    def races(self, include_suppressed: bool = False) -> list[Race]:
        with self._lock:
            return [
                r for r in self._races
                if include_suppressed or r.suppressed_reason is None
            ]

    def reset(self) -> None:
        """Drop races AND all clock/epoch state (regression tests run
        several isolated workloads in one process)."""
        with self._lock:
            self._races.clear()
            self._seen_fps.clear()
            self._vars.clear()
            self._clocks.clear()
            self._tokens.clear()
            self._token_refs.clear()
            self.ops = 0

    def report(self) -> dict[str, Any]:
        with self._lock:
            races = list(self._races)
            ops = self.ops
        return {
            "tool": "dynarace",
            "pid": os.getpid(),
            "ops": ops,
            "races": [r.to_dict() for r in races
                      if r.suppressed_reason is None],
            "suppressed": [r.to_dict() for r in races
                           if r.suppressed_reason is not None],
        }

    def dump(self, path: str) -> None:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.report(), f, indent=1)
        os.replace(tmp, path)
