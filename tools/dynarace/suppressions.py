"""State-level race suppressions, each with a WRITTEN happens-before
justification.

A race on a state listed here is recorded as *suppressed* (counted,
inspectable via ``--show-suppressed``), never gated on. The policy is
dynalint's: a suppression without a real justification is worse than a
finding, because it silences the NEXT race on the same state too —
tests/test_dynarace.py enforces that every entry names its
happens-before argument (the literal string "HB:" must appear) and that
the committed fingerprint baseline stays EMPTY (suppressions carry the
reasoning; the baseline grandfathers nothing).

These are the audited survivors of the PR-20 suppression sweep (see
tools/dynarace/SUPPRESSIONS_AUDIT.md): benign-by-construction patterns
the vector-clock model cannot see an edge for, because the edge is the
GIL plus a single-writer/single-reader protocol rather than a lock.
"""

from __future__ import annotations

# state key (registry.SHARED_STATE) -> justification. Format: one
# sentence of what races, then "HB: ..." naming why no ordering edge is
# required for correctness.
SUPPRESSED_STATES: dict[str, str] = {
    "engine.step_times": (
        "telemetry sampler drains the step-latency deque while the step "
        "thread appends. HB: none required — collections.deque append/"
        "popleft are GIL-atomic, the step thread is the only appender, "
        "the sampler the only drainer, maxlen bounds loss, and a torn "
        "window only shifts an observation into the next /metrics "
        "scrape; no engine decision reads this state"
    ),
    "engine.burst_fills": (
        "same sampler-vs-appender shape as engine.step_times. HB: same "
        "justification — GIL-atomic bounded deque, single appender "
        "(step thread), single drainer (sampler), observability-only"
    ),
}
