"""Annotation façade — re-exports the in-package shim.

Instrumented production code imports ``dynamo_tpu.runtime.race`` (so
the installed package never depends on ``tools/``); tests and tooling
may prefer this spelling:

    from tools.dynarace import annotate
    annotate.write("engine.step_times")

Both names bind the SAME functions: no-ops unless ``DYN_RACE=1``.
"""

from dynamo_tpu.runtime.race import (  # noqa: F401
    ENABLED,
    Event,
    Lock,
    Queue,
    RLock,
    acquire,
    fork,
    join,
    read,
    release,
    write,
)
