"""The reviewable concurrency registries dynarace checks against.

Same discipline as tools/dynalint/catalog.py (DL006 fault sites): adding
a tracked shared state or a named sync point is a two-line diff *here*
plus the annotation in the code, so the concurrency surface shows up in
review. Drift fails tests in both directions
(tests/test_dynarace.py):

- a ``race.read/write`` state string in the package that is not in
  ``SHARED_STATE`` (untracked state), or a catalogued state no code
  annotates (stale entry);
- a named ``race.Lock/RLock/Queue/Event`` or ``race.release/acquire``
  token site not in ``SYNC_POINTS``, or a catalogued sync point no code
  declares;
- ``SHARED_STATE`` out of sync with dynalint's copy
  (tools/dynalint/catalog.py ``SHARED_STATE``, consumed by DL005) —
  the static and dynamic layers must agree on what the cross-thread
  state IS.

Thread vocabulary (docs/CONCURRENCY.md): the **engine step thread**
(``engine-step``, owns the device), the **KVBM offload/writer threads**
(``kvbm-offload``, ``kvbm-g4-writer``), the **disagg transfer workers**,
the **telemetry sampler** (an asyncio task on the event loop), and the
**asyncio control plane** (frontend/hub/admin).
"""

from __future__ import annotations

# state key -> who touches it, under what discipline. Keys are spelled
# "owner.attr"; the attr suffix is what dynalint's DL005 sees.
SHARED_STATE: dict[str, str] = {
    "engine.step_times": (
        "engine/core.py step-latency deque — step thread appends, "
        "telemetry sampler (event loop) drains via popleft; GIL-atomic "
        "bounded deque, no lock (suppressed, see suppressions.py)"
    ),
    "engine.burst_fills": (
        "engine/core.py burst-fill deque — same single-appender/"
        "single-drainer deque discipline as engine.step_times"
    ),
    "flight.timeline": (
        "runtime/flight.py timeline ring (events/attrs/retention "
        "buckets) — step thread and event loop both enter; EVERY access "
        "must hold FlightRecorder._lock (flight.lock), including "
        "snapshot reads (the pre-dynarace snapshot-outside-lock race)"
    ),
    "kvbm.checksums": (
        "kvbm/manager.py block-checksum dict — offload thread stamps on "
        "offer, step thread reads on onboard and pops on corruption; "
        "guarded by kvbm.manager.lock (the pre-dynarace unguarded-dict "
        "race)"
    ),
    "hub.capture_log": (
        "runtime/hub_store.py compaction capture list — event-loop-only "
        "mutation; the snapshot worker thread sees state only through "
        "the hub.snapshot to_thread hand-off edge"
    ),
}

# sync-point name -> what it mediates. These are the tokens vector-clock
# edges flow through: named locks/queues/events plus ad-hoc release/
# acquire pairs (asyncio hand-offs, to_thread boundaries, thread forks).
SYNC_POINTS: dict[str, str] = {
    "engine.wake": (
        "engine/core.py step-thread wake Event — control plane (admit/"
        "drain/close/spmd-sync) -> step thread"
    ),
    "engine.out_q": (
        "engine/core.py per-request asyncio.Queue — step thread posts "
        "token deltas + sentinels via call_soon_threadsafe (_post), the "
        "generate() coroutine consumes; the release/acquire pair IS the "
        "cross-world hand-off edge"
    ),
    "engine.step-thread": (
        "engine/core.py step-thread lifecycle — fork at start() "
        "(constructor state happens-before the loop), join at close()"
    ),
    "flight.lock": (
        "runtime/flight.py FlightRecorder._lock — all timeline "
        "mutation AND snapshot reads"
    ),
    "tenancy.lock": (
        "engine/tenancy.py TenantScheduler._lock — admission lanes, "
        "buckets, vtime clocks; event loop enqueues, step thread "
        "dequeues"
    ),
    "kvbm.manager.lock": (
        "kvbm/manager.py manager RLock — stats + block checksums; "
        "re-entrant because host-pool eviction cascades re-enter "
        "through on_evict while held"
    ),
    "kvbm.host_pool.lock": "kvbm/pool.py G2 host block pool LRU lock",
    "kvbm.disk_pool.lock": "kvbm/pool.py G3 disk pool index lock",
    "kvbm.remote_tier.lock": (
        "kvbm/pool.py G4 remote tier bookkeeping lock"
    ),
    "kvbm.offload_q": (
        "kvbm/offload.py sealed-page hand-off queue — step thread "
        "submits, offload thread drains"
    ),
    "kvbm.offload_flush": (
        "kvbm/offload.py flush() completion Event — offload thread "
        "sets, caller waits"
    ),
    "kvbm.offload-thread": (
        "kvbm/offload.py offload worker lifecycle (fork/join)"
    ),
    "kvbm.remote_q": (
        "kvbm/manager.py G4 writer queue — offload thread enqueues, "
        "g4-writer thread drains toward the hub"
    ),
    "kvbm.g4-writer-thread": (
        "kvbm/manager.py G4 writer lifecycle (fork; daemon, never "
        "joined)"
    ),
    "disagg.local_sources.lock": (
        "disagg/transfer.py in-process source registry lock"
    ),
    "disagg.source.lock": (
        "disagg/transfer.py per-source export-table lock — event loop "
        "registers, transfer worker takes"
    ),
    "disagg.device_conns.lock": (
        "disagg/transfer.py PJRT connection-cache lock"
    ),
    "hub.snapshot": (
        "runtime/hub_store.py compaction to_thread boundary — loop "
        "releases before dispatching write_snapshot_tmp to the worker "
        "thread, the worker acquires on entry"
    ),
}
