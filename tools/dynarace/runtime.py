"""The enabled half of the dynarace shim: instrumented primitives bound
into ``dynamo_tpu.runtime.race`` when ``DYN_RACE=1``.

Ordering discipline (what makes the vector-clock math sound):

- lock **release** edges are recorded *while still holding* the lock —
  the clock merge must be visible before any contender can acquire;
- lock **acquire** edges are recorded *after* the real acquire;
- queue **put** records its release edge *before* the real put — the
  consumer may dequeue the item before ``put`` even returns to us. (A
  ``queue.Full`` bounce therefore leaves a spurious merge on the
  channel clock: conservative — it can only mask, never fabricate, a
  race.)
- schedule yield points run *outside* any real lock/mutex, so a
  perturbation sleep never serializes the thing it is perturbing.

Report plumbing: when ``DYN_RACE_REPORT=<dir>`` is set, every process
dumps ``race_<pid>.json`` into it at exit (hub replicas and sim workers
are subprocesses — the CLI aggregates the directory). Likewise
``DYN_RACE_TRACE=<dir>`` dumps ``trace_<pid>.txt`` when the schedule
explorer is active.
"""

from __future__ import annotations

import atexit
import os
import queue
import threading
from typing import Any

from tools.dynarace.detector import Detector
from tools.dynarace.sched import Schedule

DETECTOR = Detector()
SCHEDULE: Schedule | None = None
_seed = os.environ.get("DYN_RACE_SCHED", "")
if _seed:
    SCHEDULE = Schedule(_seed)


def _point(kind: str, site: str) -> None:
    if SCHEDULE is not None:
        SCHEDULE.point(kind, site)


# -- annotate functions (bound by dynamo_tpu/runtime/race.py) --------------


def read(state: str) -> None:
    DETECTOR.read(state)


def write(state: str) -> None:
    DETECTOR.write(state)


def acquire(token: Any, site: str = "") -> None:
    DETECTOR.acquire(token, site)
    _point("acquire", site or f"token@{id(token):x}")


def release(token: Any, site: str = "") -> None:
    DETECTOR.release(token, site)
    _point("release", site or f"token@{id(token):x}")


def fork(thread: "threading.Thread") -> None:
    DETECTOR.fork(thread)
    _point("fork", f"thread:{thread.name}")


def join(thread: "threading.Thread") -> None:
    DETECTOR.join(thread)


# -- instrumented primitives -----------------------------------------------


class Lock:
    """Instrumented ``threading.Lock``."""

    __slots__ = ("_lock", "name")

    def __init__(self, name: str = ""):
        self._lock = threading.Lock()
        self.name = name or f"lock@{id(self):x}"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _point("acquire", self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            DETECTOR.acquire(self, self.name)
        return ok

    def release(self) -> None:
        DETECTOR.release(self, self.name)
        self._lock.release()
        _point("release", self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "Lock":
        self.acquire()
        return self

    def __exit__(self, *_exc: Any) -> None:
        self.release()


class RLock:
    """Instrumented ``threading.RLock``: only the outermost acquire/
    release carry HB edges (inner recursion is same-thread program
    order). ``_depth`` is mutated only while the lock is held, so it
    needs no extra guard."""

    __slots__ = ("_lock", "_depth", "name")

    def __init__(self, name: str = ""):
        self._lock = threading.RLock()
        self._depth = 0
        self.name = name or f"rlock@{id(self):x}"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._depth == 0:
            _point("acquire", self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._depth += 1
            if self._depth == 1:
                DETECTOR.acquire(self, self.name)
        return ok

    def release(self) -> None:
        if self._depth == 1:
            DETECTOR.release(self, self.name)
        self._depth -= 1
        outermost = self._depth == 0
        self._lock.release()
        if outermost:
            _point("release", self.name)

    def __enter__(self) -> "RLock":
        self.acquire()
        return self

    def __exit__(self, *_exc: Any) -> None:
        self.release()


class Event:
    """Instrumented ``threading.Event``: ``set`` releases, a satisfied
    ``wait`` acquires. ``clear`` is untracked (it removes no ordering)."""

    __slots__ = ("_ev", "name")

    def __init__(self, name: str = ""):
        self._ev = threading.Event()
        self.name = name or f"event@{id(self):x}"

    def set(self) -> None:
        DETECTOR.release(self, self.name)
        self._ev.set()
        _point("release", self.name)

    def wait(self, timeout: float | None = None) -> bool:
        ok = self._ev.wait(timeout)
        if ok:
            DETECTOR.acquire(self, self.name)
        return ok

    def is_set(self) -> bool:
        return self._ev.is_set()

    def clear(self) -> None:
        self._ev.clear()


class Queue(queue.Queue):
    """Instrumented ``queue.Queue``: channel-granularity edges — a get
    acquires the clock of EVERY prior put, not just its own item's.
    Coarser than per-item tagging, strictly conservative (extra HB
    edges can only hide races, never invent them), and cheap."""

    def __init__(self, name: str = "", maxsize: int = 0):
        super().__init__(maxsize=maxsize)
        self.name = name or f"queue@{id(self):x}"

    def put(self, item: Any, block: bool = True,
            timeout: float | None = None) -> None:
        DETECTOR.release(self, self.name)
        super().put(item, block, timeout)
        _point("put", self.name)

    def get(self, block: bool = True,
            timeout: float | None = None) -> Any:
        item = super().get(block, timeout)
        DETECTOR.acquire(self, self.name)
        _point("got", self.name)
        return item


# -- per-process report/trace dump -----------------------------------------


def _dump_at_exit() -> None:
    report_dir = os.environ.get("DYN_RACE_REPORT", "")
    if report_dir:
        try:
            os.makedirs(report_dir, exist_ok=True)
            DETECTOR.dump(
                os.path.join(report_dir, f"race_{os.getpid()}.json")
            )
        except OSError:
            pass
    trace_dir = os.environ.get("DYN_RACE_TRACE", "")
    if trace_dir and SCHEDULE is not None:
        try:
            os.makedirs(trace_dir, exist_ok=True)
            SCHEDULE.dump(
                os.path.join(trace_dir, f"trace_{os.getpid()}.txt")
            )
        except OSError:
            pass


atexit.register(_dump_at_exit)
