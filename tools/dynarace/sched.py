"""Seeded deterministic schedule explorer.

With ``DYN_RACE_SCHED=<seed>`` set (alongside ``DYN_RACE=1``), every
instrumented sync boundary becomes a *yield point*: the wrapper calls
``point(kind, site)`` and this module decides — as a pure function of
``(seed, site, kind, n)`` where ``n`` is the occurrence index of that
(site, kind) pair — whether to perturb the schedule there, and for how
long. Same seed ⇒ same decisions ⇒ the same order-dependent bug
surfaces again; a regression test replays the interleaving by exporting
the seed.

Bias (loom/rr-style): perturbation probability is highest *just after*
a release-flavoured operation — a released lock, a just-put queue item,
a just-set event — because that is the instant an adversarial scheduler
would hand the CPU to the contending thread. Acquire-flavoured points
get a low probability so waiters still make progress.

The decision stream is also the **trace**: every point appends
``site|kind|n|decision``, and ``dump()`` writes the lines sorted by
(site, kind, n). For a fixed instrumented workload the per-(site, kind)
operation counts are schedule-independent, so the dumped trace is
byte-identical across runs with the same seed — the replay contract
tests/test_dynarace.py guards with two subprocess runs.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Iterable

# kind -> perturbation probability numerator (out of 256)
_BIAS = {
    "release": 112,  # just released a lock / set an event
    "put": 112,      # just put a queue item
    "acquire": 24,   # about to take a lock
    "got": 24,       # just dequeued
    "fork": 64,      # just started a thread
}
_DEFAULT_BIAS = 24
_MAX_SLEEP_S = 0.004


class Schedule:
    """One process's seeded perturbation state."""

    def __init__(self, seed: str):
        self.seed = seed
        self._lock = threading.Lock()
        self._counts: dict[tuple[str, str], int] = {}
        self._trace: list[tuple[str, str, int, int]] = []

    def point(self, kind: str, site: str) -> None:
        key = (site, kind)
        with self._lock:
            n = self._counts.get(key, 0)
            self._counts[key] = n + 1
        h = hashlib.sha1(
            f"{self.seed}|{site}|{kind}|{n}".encode()
        ).digest()
        go = 1 if h[0] < _BIAS.get(kind, _DEFAULT_BIAS) else 0
        with self._lock:
            self._trace.append((site, kind, n, go))
        if go:
            # 0.5ms..4ms, derived from the hash — long enough to let a
            # contending OS thread run, short enough for <10s smokes.
            # dynalint: disable=DL001 -- the blocking perturbation IS the
            # schedule explorer's contract (DYN_RACE_SCHED test mode
            # only; stalling the loop at a sync boundary is exactly the
            # adversarial reordering being explored)
            time.sleep((1 + h[1] % 8) * (_MAX_SLEEP_S / 8))
        elif h[2] < 64:
            # plain cooperative yield: cheap reordering pressure even
            # where a sleep would be too heavy
            # dynalint: disable=DL001 -- same DYN_RACE_SCHED-only
            # contract as above (sleep(0) = cooperative yield)
            time.sleep(0)

    def trace_lines(self) -> Iterable[str]:
        with self._lock:
            entries = sorted(self._trace)
        for site, kind, n, go in entries:
            yield f"{site}|{kind}|{n}|{go}"

    def dump(self, path: str) -> None:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(f"# dynarace schedule trace seed={self.seed}\n")
            for line in self.trace_lines():
                f.write(line + "\n")
        os.replace(tmp, path)
