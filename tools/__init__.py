"""Developer tooling for the dynamo-tpu repo (not shipped with the package)."""
