"""Shared SARIF 2.1.0 emitter for the repo's analysis tools.

Lifted out of tools/dynalint/cli.py (PR 19's ``--format=sarif``) so
dynalint (static findings) and dynarace (dynamic race reports) emit the
same document shape for code-scanning upload: one run, the full rule
catalog under ``tool.driver.rules``, results with physical locations,
and stable ``partialFingerprints`` (each tool's line-independent
fingerprint, so alerts track across rebases the way the baselines do).

Both callers adapt their native finding type into :class:`SarifResult`;
nothing here imports either tool.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

SCHEMA_URI = (
    "https://raw.githubusercontent.com/oasis-tcs/"
    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"
)


@dataclass
class SarifRule:
    """One catalog entry for ``tool.driver.rules``."""

    id: str
    name: str
    short: str
    full: str
    level: str = "error"


@dataclass
class SarifResult:
    """One finding with its physical location and fingerprint."""

    rule_id: str
    message: str
    uri: str  # repo-relative path
    line: int  # 1-based
    col: int  # 1-based
    fingerprint: str
    level: str = "error"
    # extra location frames (e.g. the OTHER side of a race), rendered
    # as additional locations on the same result
    related: list[tuple[str, int, str]] = field(default_factory=list)


def _location(uri: str, line: int, col: int, message: str | None = None):
    loc: dict = {
        "physicalLocation": {
            "artifactLocation": {"uri": uri, "uriBaseId": "SRCROOT"},
            "region": {"startLine": max(int(line), 1),
                       "startColumn": max(int(col), 1)},
        },
    }
    if message:
        loc["message"] = {"text": message}
    return loc


def render(
    tool_name: str,
    info_uri: str,
    rules: list[SarifRule],
    results: list[SarifResult],
    fingerprint_key: str,
) -> str:
    """One SARIF 2.1.0 document as an indented JSON string."""
    rule_index = {r.id: i for i, r in enumerate(rules)}
    sarif_rules = [
        {
            "id": r.id,
            "name": r.name,
            "shortDescription": {"text": r.short},
            "fullDescription": {"text": r.full},
            "defaultConfiguration": {"level": r.level},
        }
        for r in rules
    ]
    sarif_results = []
    for f in results:
        entry = {
            "ruleId": f.rule_id,
            "ruleIndex": rule_index.get(f.rule_id, -1),
            "level": f.level,
            "message": {"text": f.message},
            "locations": [_location(f.uri, f.line, f.col)],
            "partialFingerprints": {fingerprint_key: f.fingerprint},
        }
        if f.related:
            entry["relatedLocations"] = [
                _location(uri, line, 1, msg)
                for uri, line, msg in f.related
            ]
        sarif_results.append(entry)
    return json.dumps({
        "$schema": SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "informationUri": info_uri,
                "rules": sarif_rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": sarif_results,
        }],
    }, indent=2)
